"""Model-family tests: LMM (config 3), GMM (config 4), BNN (config 5).

Each model is validated by (a) parameter-recovery on synthetic data with the
standard NUTS/HMC sampler at small scale, and (b) shape/finite checks on the
flattened potential so the bijector plumbing (simplex, ordered, exp) is
exercised end-to-end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import stark_tpu
from stark_tpu.model import flatten_model
from stark_tpu.models import (
    BayesianMLP,
    GaussianMixture,
    LinearMixedModel,
    synth_bnn_data,
    synth_gmm_data,
    synth_lmm_data,
)


def test_lmm_potential_and_shapes():
    model = LinearMixedModel(num_features=3, num_groups=20, num_random=2)
    data, _ = synth_lmm_data(jax.random.PRNGKey(0), 200, 3, 20)
    fm = flatten_model(model)
    assert fm.ndim == 1 + 3 + 20 * 2 + 2 + 1
    z = jax.random.normal(jax.random.PRNGKey(1), (fm.ndim,))
    pe, grad = fm.potential_and_grad(z, data)
    assert np.isfinite(float(pe))
    assert np.all(np.isfinite(np.asarray(grad)))


@pytest.mark.slow
def test_lmm_recovers_beta():
    model = LinearMixedModel(num_features=2, num_groups=30, num_random=2)
    data, true = synth_lmm_data(jax.random.PRNGKey(2), 1500, 2, 30, noise=0.3)
    post = stark_tpu.sample(
        model, data, chains=2, kernel="nuts", max_tree_depth=8,
        num_warmup=400, num_samples=400, seed=0,
    )
    assert post.max_rhat() < 1.1
    beta_mean = post.draws["beta"].mean(axis=(0, 1))
    np.testing.assert_allclose(beta_mean, np.asarray(true["beta"]), atol=0.15)
    sigma_mean = post.draws["sigma"].mean()
    assert abs(sigma_mean - 0.3) < 0.1


def test_gmm_potential_finite_and_simplex():
    model = GaussianMixture(num_components=4)
    data, _ = synth_gmm_data(jax.random.PRNGKey(3), 256, 4)
    fm = flatten_model(model)
    # K weights (K-1 unconstrained) + K mus + K sigmas
    assert fm.ndim == 3 + 4 + 4
    z = 0.5 * jax.random.normal(jax.random.PRNGKey(4), (fm.ndim,))
    pe, grad = fm.potential_and_grad(z, data)
    assert np.isfinite(float(pe))
    assert np.all(np.isfinite(np.asarray(grad)))
    params = fm.constrain(z)
    np.testing.assert_allclose(float(params["weights"].sum()), 1.0, rtol=1e-5)
    assert np.all(np.diff(np.asarray(params["mu"])) > 0)  # ordered


@pytest.mark.slow
def test_gmm_recovers_means_hmc():
    k = 3
    model = GaussianMixture(num_components=k)
    data, true = synth_gmm_data(jax.random.PRNGKey(5), 1024, k)
    post = stark_tpu.sample(
        model, data, chains=2, kernel="nuts", max_tree_depth=8,
        num_warmup=500, num_samples=500, seed=1,
    )
    mu_mean = np.sort(post.draws["mu"].mean(axis=(0, 1)))
    np.testing.assert_allclose(mu_mean, np.sort(np.asarray(true["mu"])), atol=0.5)


@pytest.mark.slow
def test_bnn_sghmc_predictive_accuracy():
    model = BayesianMLP(num_features=4, hidden=8)
    data, _ = synth_bnn_data(jax.random.PRNGKey(6), 2000, 4, hidden=4)
    post = stark_tpu.sghmc_sample(
        model, data, batch_size=256, chains=2,
        num_warmup=1500, num_samples=500,
        step_size=2e-3, friction=5.0, seed=2,
    )
    assert post.num_divergent == 0
    # Bayesian model averaging over thinned draws (mean PARAMETERS are
    # meaningless under the MLP's sign/permutation symmetry)
    thinned = {k: jnp.asarray(v[:, ::25]) for k, v in post.draws.items()}
    flat = {k: v.reshape((-1,) + v.shape[2:]) for k, v in thinned.items()}
    probs = jax.vmap(
        lambda p: jax.nn.sigmoid(model.forward(p, data["x"]))
    )({k: flat[k] for k in flat}).mean(axis=0)
    acc = float(((probs > 0.5) == (data["y"] > 0.5)).mean())
    assert acc > 0.8, f"posterior-predictive accuracy {acc}"

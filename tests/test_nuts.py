import jax
import jax.numpy as jnp
import numpy as np

from stark_tpu.kernels.base import init_state
from stark_tpu.kernels.nuts import nuts_step


def test_nuts_std_normal_moments():
    d = 10
    potential = lambda z: 0.5 * jnp.sum(z * z)
    inv_mass = jnp.ones(d)
    state = init_state(potential, jnp.zeros(d))

    def step(st, key):
        st, info = nuts_step(key, st, potential, jnp.asarray(0.3), inv_mass, 8)
        return st, (st.z, info.num_grad_evals)

    keys = jax.random.split(jax.random.PRNGKey(0), 3000)
    _, (zs, ngrad) = jax.lax.scan(jax.jit(step), state, keys)
    zs = np.asarray(zs)[500:]
    assert np.all(np.abs(zs.mean(0)) < 0.15)
    assert np.all(np.abs(zs.var(0) - 1.0) < 0.25)
    # trajectories should actually expand (more than 1 leaf on average)
    assert float(np.asarray(ngrad).mean()) > 3


def test_nuts_correlated_gaussian():
    # anisotropic target exercises the u-turn criterion harder
    scales = jnp.array([0.2, 1.0, 5.0])
    potential = lambda z: 0.5 * jnp.sum((z / scales) ** 2)
    inv_mass = jnp.ones(3)
    state = init_state(potential, jnp.zeros(3))

    def step(st, key):
        st, info = nuts_step(key, st, potential, jnp.asarray(0.1), inv_mass, 10)
        return st, st.z

    keys = jax.random.split(jax.random.PRNGKey(1), 6000)
    _, zs = jax.lax.scan(jax.jit(step), state, keys)
    zs = np.asarray(zs)[1000:]
    np.testing.assert_allclose(zs.std(0), np.asarray(scales), rtol=0.25)
    assert np.all(np.abs(zs.mean(0)) < 0.3 * np.asarray(scales))


def test_nuts_divergence_flag():
    # absurdly large step size on a narrow target must flag divergence
    potential = lambda z: 0.5 * jnp.sum((z / 0.01) ** 2)
    state = init_state(potential, jnp.full((2,), 0.02))
    _, info = jax.jit(
        lambda k, s: nuts_step(k, s, potential, jnp.asarray(10.0), jnp.ones(2), 5)
    )(jax.random.PRNGKey(2), state)
    assert bool(info.is_divergent)

"""Fused Pallas logistic kernel vs autodiff oracle (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np

import stark_tpu
from stark_tpu.model import flatten_model
from stark_tpu.models import Logistic, synth_logistic_data
from stark_tpu.ops import logistic_loglik_value_and_grad
import pytest


def _autodiff_oracle(beta, x, y):
    def ll(b):
        logits = x @ b
        return jnp.sum(
            y * jax.nn.log_sigmoid(logits) + (1 - y) * jax.nn.log_sigmoid(-logits)
        )

    return jax.value_and_grad(ll)(beta)


def test_fused_matches_autodiff():
    key = jax.random.PRNGKey(0)
    for n, d in [(100, 3), (1024, 8), (1500, 130)]:  # un/aligned rows+lanes
        data, _ = synth_logistic_data(jax.random.PRNGKey(n), n, d)
        beta = 0.5 * jax.random.normal(key, (d,))
        v1, g1 = logistic_loglik_value_and_grad(
            beta, data["x"].T, data["y"], lane_tile=256
        )
        v2, g2 = _autodiff_oracle(beta, data["x"], data["y"])
        np.testing.assert_allclose(float(v1), float(v2), rtol=2e-5)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=2e-4, atol=2e-4)


def test_offset_op_grads_match_autodiff():
    """custom_vjp fused op == plain autodiff through gather + non-centering."""
    from stark_tpu.models import FusedHierLogistic, HierLogistic

    data, _ = synth_logistic_data(jax.random.PRNGKey(4), 600, 5, num_groups=12)
    data = jax.tree.map(jnp.asarray, data)
    ref_model, fus_model = HierLogistic(5, 12), FusedHierLogistic(5, 12)
    ref_fm = flatten_model(ref_model)
    fus_fm = flatten_model(fus_model)
    z = 0.3 * jax.random.normal(jax.random.PRNGKey(5), (ref_fm.ndim,))
    va, ga = ref_fm.potential_and_grad(z, data)
    vf, gf = fus_fm.potential_and_grad(z, fus_model.prepare_data(data))
    np.testing.assert_allclose(float(va), float(vf), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gf), rtol=1e-3, atol=1e-4)


@pytest.mark.slow
def test_fused_hier_sampling_vmapped():
    """Fused hierarchical model samples under vmap'd NUTS (the real path)."""
    from stark_tpu.models import FusedHierLogistic

    model = FusedHierLogistic(num_features=3, num_groups=8)
    data, _ = synth_logistic_data(jax.random.PRNGKey(6), 512, 3, num_groups=8)
    post = stark_tpu.sample(
        model, data, chains=2, kernel="nuts", max_tree_depth=6,
        num_warmup=150, num_samples=150, seed=0,
    )
    assert np.all(np.isfinite(post.draws["beta"]))
    assert post.max_rhat() < 1.3


@pytest.mark.slow
def test_fused_flat_model_sampling():
    """NUTS through the fused potential reproduces the autodiff posterior."""
    from stark_tpu.models import FusedLogistic

    model = Logistic(num_features=4)
    fused_model = FusedLogistic(num_features=4)
    data, true = synth_logistic_data(jax.random.PRNGKey(1), 2048, 4)
    data = jax.tree.map(jnp.asarray, data)
    data_t = fused_model.prepare_data(data)
    fm = flatten_model(model)
    fm_fused = flatten_model(fused_model)

    pot_a = fm.bind(data)
    pot_f = fm_fused.bind(data_t)
    z = jnp.asarray([0.1, -0.2, 0.3, 0.0])
    va, ga = pot_a.value_and_grad(z)
    vf, gf = pot_f.value_and_grad(z)
    np.testing.assert_allclose(float(va), float(vf), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gf), rtol=1e-4, atol=1e-4)

    from stark_tpu.sampler import SamplerConfig, make_chain_runner

    cfg = SamplerConfig(kernel="nuts", max_tree_depth=6, num_warmup=200, num_samples=200)
    runner = jax.jit(jax.vmap(make_chain_runner(fm_fused, cfg), in_axes=(0, 0, None)))
    keys = jax.random.split(jax.random.PRNGKey(2), 2)
    z0 = 0.1 * jax.random.normal(jax.random.PRNGKey(3), (2, 4))
    res = runner(keys, z0, data_t)
    draws = np.asarray(res.draws)  # (2, 200, 4)
    assert np.all(np.isfinite(draws))
    np.testing.assert_allclose(
        draws.mean(axis=(0, 1)), np.asarray(true["beta"]), atol=0.3
    )


@pytest.mark.slow
def test_fused_model_all_entry_points():
    """Every row-splitting entry point honors prepare_data + data_row_axes.

    Regression: consensus/SG-HMC/sharded once bypassed Model.prepare_data
    (KeyError 'xT'), and a naive fix would have split the transposed xT
    along features instead of rows."""
    from stark_tpu.backends.sharded import ShardedBackend
    from stark_tpu.models import FusedLogistic
    from stark_tpu.parallel.consensus import consensus_sample
    from stark_tpu.parallel.mesh import make_mesh
    from stark_tpu.sghmc import sghmc_sample

    data, true = synth_logistic_data(jax.random.PRNGKey(0), 2048, 4)
    beta_true = np.asarray(true["beta"])

    post = consensus_sample(
        FusedLogistic(4), data, num_shards=2, chains=2, kernel="nuts",
        max_tree_depth=5, num_warmup=100, num_samples=100, seed=0,
    )
    np.testing.assert_allclose(
        np.asarray(post.draws["beta"]).mean((0, 1)), beta_true, atol=0.35
    )

    post = sghmc_sample(
        FusedLogistic(4), data, batch_size=256, chains=2, num_warmup=100,
        num_samples=200, step_size=5e-4, seed=0,
    )
    assert np.all(np.isfinite(np.asarray(post.draws["beta"])))

    mesh = make_mesh({"data": 4, "chains": 2})
    post = stark_tpu.sample(
        FusedLogistic(4), data, backend=ShardedBackend(mesh), chains=2,
        kernel="nuts", max_tree_depth=5, num_warmup=100, num_samples=100,
        seed=0,
    )
    np.testing.assert_allclose(
        np.asarray(post.draws["beta"]).mean((0, 1)), beta_true, atol=0.35
    )


@pytest.mark.slow
def test_chain_batched_vmap_matches_per_chain():
    """vmap over chains must hit the chain-batched kernel and agree with
    per-chain evaluation (both no-offset and offset variants, C not a
    multiple of the sublane pad)."""
    from stark_tpu.ops.logistic_fused import (
        logistic_loglik,
        logistic_offset_loglik,
    )

    key = jax.random.PRNGKey(1)
    n, d, C = 700, 5, 5  # ragged lanes AND ragged chain count
    data, _ = synth_logistic_data(jax.random.PRNGKey(2), n, d)
    xt, y = data["x"].T, data["y"]
    betas = 0.5 * jax.random.normal(key, (C, d))
    offs = 0.3 * jax.random.normal(jax.random.PRNGKey(3), (C, n))

    # values
    v_b = jax.vmap(lambda b: logistic_loglik(b, xt, y))(betas)
    v_s = jnp.stack([logistic_loglik(b, xt, y) for b in betas])
    np.testing.assert_allclose(np.asarray(v_b), np.asarray(v_s), rtol=2e-5)

    # gradients through the custom VJP under vmap
    g_b = jax.vmap(jax.grad(lambda b: logistic_loglik(b, xt, y)))(betas)
    g_s = jnp.stack([jax.grad(lambda b: logistic_loglik(b, xt, y))(b) for b in betas])
    np.testing.assert_allclose(np.asarray(g_b), np.asarray(g_s), rtol=2e-4, atol=2e-4)

    # offset variant: value + both grads
    f = lambda b, o: logistic_offset_loglik(b, o, xt, y)
    v_b = jax.vmap(f)(betas, offs)
    v_s = jnp.stack([f(b, o) for b, o in zip(betas, offs)])
    np.testing.assert_allclose(np.asarray(v_b), np.asarray(v_s), rtol=2e-5)
    gb_b, go_b = jax.vmap(jax.grad(f, argnums=(0, 1)))(betas, offs)
    gb_s = jnp.stack([jax.grad(f, argnums=0)(b, o) for b, o in zip(betas, offs)])
    go_s = jnp.stack([jax.grad(f, argnums=1)(b, o) for b, o in zip(betas, offs)])
    np.testing.assert_allclose(np.asarray(gb_b), np.asarray(gb_s), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(go_b), np.asarray(go_s), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_chain_batched_model_sampling_matches_unbatched_model():
    """FusedLogistic sampled with vmapped chains == plain Logistic."""
    from stark_tpu.models import FusedLogistic

    data, _ = synth_logistic_data(jax.random.PRNGKey(5), 800, 4)
    kw = dict(chains=5, kernel="nuts", max_tree_depth=5, num_warmup=200,
              num_samples=200, seed=0)
    post_f = stark_tpu.sample(FusedLogistic(num_features=4), dict(data), **kw)
    post_p = stark_tpu.sample(Logistic(num_features=4), dict(data), **kw)
    np.testing.assert_allclose(
        np.asarray(post_f.draws["beta"]).mean((0, 1)),
        np.asarray(post_p.draws["beta"]).mean((0, 1)),
        atol=0.05,
    )


def test_gaussian_offset_loglik_matches_autodiff():
    """Fused gaussian link (one-pass SSR + X-resid): value and all five
    gradients (beta, offsets, sigma via custom_vjp) match autodiff."""
    import jax
    import jax.numpy as jnp
    import jax.scipy.stats as jstats
    import numpy as np

    from stark_tpu.ops.logistic_fused import gaussian_offset_loglik

    n, d = 3333, 5  # ragged last lane tile on purpose
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, d))
    beta = 0.3 * jax.random.normal(jax.random.PRNGKey(1), (d,))
    off = 0.5 * jax.random.normal(jax.random.PRNGKey(2), (n,))
    y = x @ beta + off + 0.4 * jax.random.normal(jax.random.PRNGKey(3), (n,))
    sigma = jnp.asarray(0.7)

    def ref(beta, off, sigma):
        return jnp.sum(jstats.norm.logpdf(y, x @ beta + off, sigma))

    def fused(beta, off, sigma):
        return gaussian_offset_loglik(beta, off, x.T, y, sigma)

    v_r, g_r = jax.value_and_grad(ref, argnums=(0, 1, 2))(beta, off, sigma)
    v_f, g_f = jax.value_and_grad(fused, argnums=(0, 1, 2))(beta, off, sigma)
    np.testing.assert_allclose(float(v_f), float(v_r), rtol=2e-5)
    for a, b in zip(g_f, g_r):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )

    # chain-batched: vmap over (beta, off, sigma) shares one X pass
    C = 6
    betas = 0.3 * jax.random.normal(jax.random.PRNGKey(4), (C, d))
    offs = 0.5 * jax.random.normal(jax.random.PRNGKey(5), (C, n))
    sigmas = jnp.linspace(0.5, 1.2, C)
    v_fb, g_fb = jax.vmap(
        jax.value_and_grad(fused, argnums=(0, 1, 2))
    )(betas, offs, sigmas)
    v_rb, g_rb = jax.vmap(
        jax.value_and_grad(ref, argnums=(0, 1, 2))
    )(betas, offs, sigmas)
    np.testing.assert_allclose(np.asarray(v_fb), np.asarray(v_rb), rtol=2e-5)
    for a, b in zip(g_fb, g_rb):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )

"""`tools/perf_ledger.py check` wired into the test tier (ROADMAP item 3's
"wire it into CI" note): the committed ledger must pass the gate, and a
synthetic regression must fail it — so a bench round that lands a slower
row breaks the suite instead of shipping silently.
"""

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOL = os.path.join(_REPO, "tools", "perf_ledger.py")
_LEDGER = os.path.join(_REPO, "bench_artifacts", "ledger.jsonl")


def _check(*args, env_extra=None):
    env = dict(os.environ)
    # the read path must not need an accelerator (or jax at all)
    env["JAX_PLATFORMS"] = "cpu"
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, _TOOL, *args],
        capture_output=True, text=True, env=env, cwd=_REPO,
    )


def test_committed_ledger_exists_and_passes():
    """The repo ships a real ledger (the bench legs append to it) and the
    CI gate accepts its current state — every config present."""
    assert os.path.exists(_LEDGER), (
        "bench_artifacts/ledger.jsonl must be committed so the regression "
        "gate has a baseline"
    )
    rows = [json.loads(l) for l in open(_LEDGER) if l.strip()]
    assert rows, "committed ledger must hold at least one row"
    res = _check("check", "--all-configs")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "REGRESSION" not in res.stdout


def _row(config, ess, ts):
    return {
        "schema": 1, "ts": ts, "source": "test", "config": config,
        "ess_per_sec": ess, "wall_s": 10.0, "max_rhat": 1.005,
        "converged": True,
    }


def test_synthetic_regression_fails(tmp_path):
    """A 2x throughput drop against a healthy trailing median exits 1;
    reverting it exits 0 — the ratchet both bites and releases."""
    path = tmp_path / "ledger.jsonl"
    t0 = time.time()
    rows = [_row("cfg", 10.0, t0 + i) for i in range(4)]
    rows.append(_row("cfg", 5.0, t0 + 9))  # 2x drop, ~3x past the band
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    res = _check("--ledger", str(path), "check")
    assert res.returncode == 1, res.stdout + res.stderr
    assert "REGRESSION" in res.stdout
    # a healthy newest row passes again
    rows.append(_row("cfg", 10.5, t0 + 10))
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    res = _check("--ledger", str(path), "check")
    assert res.returncode == 0, res.stdout + res.stderr


def test_nutssched_rows_committed():
    """The ragged-NUTS scheduling series is part of the gated ledger: a
    committed ``nutssched:*`` row exists, its newest entry passed the
    bench's own gate with the claimed >=1.3x occupancy-adjusted speedup
    and a strictly-better lane occupancy, and both fleet scheduler
    variants (legacy depth-5 cap + ragged lifted-depth) are recorded."""
    rows = [json.loads(l) for l in open(_LEDGER) if l.strip()]
    sched = [r for r in rows if r["config"].startswith("nutssched:")]
    assert sched, "committed ledger must carry a nutssched:* row"
    newest = sched[-1]
    assert newest["converged"] is True
    assert newest["bit_identical"] is True
    assert newest["speedup_vs_legacy"] >= 1.3
    assert (
        newest["lane_occupancy_ragged"] > newest["lane_occupancy_legacy"]
    )
    fleet_cfgs = {
        r["config"] for r in rows
        if r["config"].startswith("fleet:eight_schools:")
    }
    assert any(":sched=ragged:" in c for c in fleet_cfgs), (
        "fleet ledger must record the ragged-scheduler (lifted depth cap) "
        "variant"
    )
    assert any(":sched=ragged:" not in c for c in fleet_cfgs), (
        "fleet ledger must keep the legacy depth-capped series too"
    )


def test_fleet_stream_rows_committed():
    """The churn-heavy streaming-fleet series (PR 13) is part of the
    gated ledger: slotted, legacy-compaction, and warm-started rows all
    committed at equal problem sets; the newest slotted row holds the
    zero-recompile evidence (exactly ONE batched-scan compile, zero
    compactions, steady-state occupancy >= 0.9 with a live queue) at an
    aggregate min-ESS/s at or above the legacy-compaction baseline; the
    legacy row records the >= 2 specializations the slot scheduler
    exists to avoid; and the warm-start row records its warmup savings
    with an honest-null speedup where transfer doesn't pay."""
    rows = [json.loads(l) for l in open(_LEDGER) if l.strip()]
    stream = [r for r in rows
              if r["config"].startswith("fleet:stream:eight_schools:")]
    assert stream, "committed ledger must carry fleet:stream:* rows"

    def newest(sched):
        series = [r for r in stream if f":sched={sched}:" in r["config"]]
        assert series, f"missing fleet:stream sched={sched} series"
        return series[-1]

    slots = newest("slots")
    compact = newest("compact")
    ws = newest("slots_warmstart")
    assert slots["converged"] is True
    assert slots["block_scan_compiles"] == 1
    assert slots["compactions"] == 0
    assert slots["occupancy_streaming"] >= 0.9
    assert compact["block_scan_compiles"] >= 2
    assert slots["ess_per_sec"] >= compact["ess_per_sec"]
    assert ws["warmup_draws_saved"] is not None
    if ws["warmstart_speedup"] is not None:
        # when the row claims a payoff it must be a real one
        assert ws["warmstart_speedup"] > 1.0
    elif ws["converged"] is not True:
        # honest-null discipline: a warm-start leg that loses its gate
        # records missing data, never a measured zero
        assert ws["ess_per_sec"] is None


def test_fleet_mesh_rows_committed():
    """The device-parallel fleet series (PR 14) is part of the gated
    ledger: a committed ``fleet:mesh:eight_schools:*`` row from the
    forced 8-device CPU mesh exists, its problems all converged with
    per-problem draws BIT-IDENTICAL to the single-device fleet at equal
    B, and both rates are recorded.  The >=2x aggregate min-ESS/s gate
    is the accelerator's number: on this 1-core container 8 virtual
    devices share one core, so a gate-losing row records an honest null
    (never a fabricated speedup) while the correctness evidence rides
    the row — the established null-not-0.0 rule."""
    rows = [json.loads(l) for l in open(_LEDGER) if l.strip()]
    mesh = [r for r in rows
            if r["config"].startswith("fleet:mesh:eight_schools:")]
    assert mesh, "committed ledger must carry a fleet:mesh:* row"
    newest = mesh[-1]
    assert newest["shards"] >= 2
    assert newest["bit_identical"] is True, (
        "mesh fleet draws diverged from the single-device fleet"
    )
    assert newest["converged_fraction"] >= 0.95
    assert newest["mesh_ess_per_sec"] is not None
    assert newest["single_device_ess_per_sec"] is not None
    if newest["converged"] is True:
        # a row claiming the full gate must hold the 2x speedup
        assert newest["speedup_vs_single_device"] >= 2.0
    else:
        # honest-null discipline: losing the rate gate records missing
        # data in the value column, never a measured zero
        assert newest["ess_per_sec"] is None


def test_quantized_fusedvg_rows_committed():
    """The quantized data-plane's ledger evidence: committed
    ``fusedvg:*:x=int8`` and ``:x=fp8e4m3`` rows exist for the
    memory-bound families (lmm, irt, logistic), each carrying the
    bytes-accounting columns with the >=2x traffic reduction; at least
    one of lmm/irt holds the >=1.3x value-and-grad gate under a
    quantized X; and any gate-failing quantized row follows the
    null-not-0.0 rule (honest parity, never a hidden regression)."""
    rows = [json.loads(l) for l in open(_LEDGER) if l.strip()]
    quant = [
        r for r in rows
        if r["config"].startswith("fusedvg:")
        and (":x=int8" in r["config"] or ":x=fp8e4m3" in r["config"])
    ]
    for fam in ("lmm", "irt", "logistic"):
        for dt in ("int8", "fp8e4m3"):
            series = [
                r for r in quant
                if r["config"].startswith(f"fusedvg:{fam}:")
                and r["config"].endswith(f":x={dt}")
            ]
            assert series, (
                f"committed ledger must carry a fusedvg:{fam}:…:x={dt} row"
            )
            newest = series[-1]
            assert newest["x_bytes_per_grad"] is not None
            assert newest["x_traffic_reduction"] >= 2.0
            if newest["converged"] is not True:
                # the null-not-0.0 rule: a quantized leg that loses its
                # gate records missing data, never a measured zero
                assert newest["ess_per_sec"] is None
    gated = [
        r for r in quant
        if r["config"].split(":", 2)[1] in ("lmm", "irt")
        and r["converged"] is True
    ]
    assert any(r["speedup_vs_autodiff"] >= 1.3 for r in gated), (
        "at least one memory-bound family must hold the >=1.3x "
        "value-and-grad gate under a quantized X stream"
    )


def test_serving_read_rows_committed():
    """The posterior-serving read plane's ledger evidence (``bench.py
    microbench serving``): committed ``read:summary:*``,
    ``read:predict:*`` and ``read:reconverge:*`` rows exist, and each
    newest row either holds its own acceptance gate — >=10x warm-LRU
    summary QPS, >=5x batched predictive throughput at parity with a
    quantized-X tenant named on the row, and an eight-schools
    incremental resubmit that saved draws — or follows the honest-null
    rule (a gate-losing leg records missing data in the value column,
    never a measured zero)."""
    rows = [json.loads(l) for l in open(_LEDGER) if l.strip()]

    def newest(prefix):
        series = [r for r in rows if r["config"].startswith(prefix)]
        assert series, f"committed ledger must carry a {prefix}* row"
        return series[-1]

    summ = newest("read:summary:")
    if summ["converged"] is True:
        assert summ["warm_cold_speedup"] >= 10.0
        assert summ["summary_qps_warm"] > summ["summary_qps_cold"]
        assert summ["cache_hit_ratio"] > 0.0
    else:
        assert summ["ess_per_sec"] is None

    pred = newest("read:predict:")
    # the quantized tenant rides the row whether or not the gate held:
    # the scale-fold identity is correctness evidence, not throughput
    assert pred["quantized_tenant"]
    assert pred["predict_parity_abs_err"] is not None
    assert pred["predict_parity_abs_err"] <= 1e-5
    if pred["converged"] is True:
        assert pred["speedup_vs_loop"] >= 5.0
        assert pred["batched_evals_per_sec"] > pred["loop_evals_per_sec"]
    else:
        assert pred["ess_per_sec"] is None

    reconv = newest("read:reconverge:")
    if reconv["converged"] is True:
        assert reconv["reconverge_draws_saved"] > 0
        assert reconv["warmstarted"] is True
        assert (
            reconv["warm_total_draws_per_chain"]
            < reconv["cold_total_draws_per_chain"]
        )
    else:
        assert reconv["ess_per_sec"] is None


def test_fresh_config_passes(tmp_path):
    """A config with no history must not fail CI (fresh ledgers pass)."""
    path = tmp_path / "ledger.jsonl"
    path.write_text(json.dumps(_row("new-config", 3.0, time.time())) + "\n")
    res = _check("--ledger", str(path), "check")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "insufficient history" in res.stdout


def test_autotune_row_committed():
    """The autotuner's committed ``autotune:*`` row is honest-null
    provenance: it measures nothing gateable (ess_per_sec null, never
    0.0), ``converged`` carries the parity verdict, the chosen profile
    id is stamped, and the mining counts are recorded (skipped evidence
    is counted, not silent)."""
    rows = [json.loads(l) for l in open(_LEDGER) if l.strip()]
    auto = [r for r in rows
            if str(r.get("config", "")).startswith("autotune:")]
    assert auto, "committed ledger must carry an autotune:* row"
    newest = auto[-1]
    assert newest["ess_per_sec"] is None       # null-not-0.0
    assert newest["converged"] is True         # the parity verdict
    assert isinstance(newest["profile"], str) and "#" in newest["profile"]
    assert isinstance(newest.get("fingerprint"), str)
    assert newest["profile"].startswith(newest["fingerprint"])
    assert newest["parity_cells"] > 0
    for key in ("mined_rows", "stale_rows_skipped",
                "fingerprint_mismatch_rows"):
        assert isinstance(newest[key], int)
    # the committed profile the row points at exists and loads
    prof_path = os.path.join(
        _REPO, "bench_artifacts", "profiles",
        f"{newest['fingerprint']}.json",
    )
    assert os.path.exists(prof_path), prof_path
    sys.path.insert(0, _REPO)
    from stark_tpu import profile

    loaded = profile.load_profile(prof_path)
    assert loaded["id"] == newest["profile"]

"""Async block pipeline: sync-vs-pipelined equivalence, overlap telemetry,
draw-major DrawStore appends, the DrawHistory buffer, and the workdir-keyed
compilation cache.

The pipeline's contract (runner.py): with the overlap ON (default) and OFF
(``STARK_SYNC_BLOCKS=1`` / ``sync_blocks=True``) the draws, the metrics
history, the checkpoint contents, and the draw-store bytes are
BIT-IDENTICAL — only wall-clock attribution differs.  These tests hold
that equivalence for both the per-chain (NUTS/HMC) and the ChEES ensemble
paths, and pin the new trace fields bench.py / trace_report consume.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

import stark_tpu
from stark_tpu import diagnostics, faults
from stark_tpu.checkpoint import load_checkpoint
from stark_tpu.drawstore import DrawStore, read_draws
from stark_tpu.model import Model, ParamSpec
from stark_tpu.telemetry import RunTrace, read_trace, summarize_trace


class StdNormal2(Model):
    def param_spec(self):
        return {"x": ParamSpec((2,))}

    def log_prior(self, p):
        return -0.5 * jnp.sum(p["x"] ** 2)

    def log_lik(self, p, data):
        return jnp.zeros(())


#: semantic metrics fields (timing attribution legitimately differs
#: between the pipelined and serial loops)
_TIMING_KEYS = ("wall_s", "t_dispatch_s", "t_diag_s")


def _strip_timing(history):
    return [
        {k: v for k, v in rec.items() if k not in _TIMING_KEYS}
        for rec in history
    ]


def _run_both_modes(tmp_path, **kw):
    """One run per mode with full persistence; returns (pipelined, sync,
    paths dict)."""
    out = {}
    for mode in ("pipe", "sync"):
        d = tmp_path / mode
        d.mkdir()
        paths = {
            "ckpt": str(d / "c.npz"),
            "store": str(d / "d.stkr"),
            "metrics": str(d / "m.jsonl"),
        }
        post = stark_tpu.sample_until_converged(
            StdNormal2(),
            checkpoint_path=paths["ckpt"],
            draw_store_path=paths["store"],
            metrics_path=paths["metrics"],
            sync_blocks=(mode == "sync"),
            **kw,
        )
        out[mode] = (post, paths)
    return out


def _assert_equivalent(out):
    post_p, paths_p = out["pipe"]
    post_s, paths_s = out["sync"]
    # draws bit-identical
    np.testing.assert_array_equal(post_p.draws_flat, post_s.draws_flat)
    # metrics history identical up to timing attribution
    assert _strip_timing(post_p.history) == _strip_timing(post_s.history)
    # checkpoint contents bit-identical (arrays AND accounting meta)
    ap, mp = load_checkpoint(paths_p["ckpt"])
    as_, ms = load_checkpoint(paths_s["ckpt"])
    assert set(ap) == set(as_)
    for k in ap:
        np.testing.assert_array_equal(ap[k], as_[k], err_msg=k)
    for k in ("blocks_done", "block_size", "draw_rows", "num_divergent",
              "kernel"):
        assert mp[k] == ms[k], k
    # draw-store files byte-identical (covers the draw-major chees append)
    with open(paths_p["store"], "rb") as f:
        b_p = f.read()
    with open(paths_s["store"], "rb") as f:
        b_s = f.read()
    assert b_p == b_s


@pytest.mark.slow  # >=8s on the 1-core host (pytest.ini policy, re-profiled 2026-08-03)
def test_pipeline_matches_sync_nuts(tmp_path):
    out = _run_both_modes(
        tmp_path, chains=2, block_size=25, max_blocks=3, min_blocks=3,
        rhat_target=0.0, num_warmup=50, kernel="nuts", max_tree_depth=4,
        seed=0,
    )
    _assert_equivalent(out)


def test_pipeline_matches_sync_chees(tmp_path):
    out = _run_both_modes(
        tmp_path, chains=4, block_size=20, max_blocks=3, min_blocks=3,
        rhat_target=0.0, num_warmup=40, kernel="chees", map_init_steps=5,
        seed=1,
    )
    _assert_equivalent(out)


def test_sync_env_escape_hatch(tmp_path, monkeypatch):
    """STARK_SYNC_BLOCKS=1 selects the serial loop without code changes;
    the trace records which mode ran."""
    monkeypatch.setenv("STARK_SYNC_BLOCKS", "1")
    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        stark_tpu.sample_until_converged(
            StdNormal2(), chains=2, block_size=20, max_blocks=2,
            min_blocks=2, rhat_target=0.0, num_warmup=30, kernel="hmc",
            num_leapfrog=4, seed=0, trace=tr,
        )
    blocks = [e for e in read_trace(str(p)) if e["event"] == "sample_block"]
    assert blocks and all(e["pipelined"] is False for e in blocks)


def test_trace_overlap_fields_wellformed(tmp_path):
    """Tier-1 regression for the overlap schema: a traced smoke run emits
    t_host_hidden_s / device_idle_s / t_wait_s on every sample_block, all
    finite and >= 0, and summarize_trace aggregates them into a
    well-formed device-idle fraction."""
    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        stark_tpu.sample_until_converged(
            StdNormal2(), chains=2, block_size=20, max_blocks=3,
            min_blocks=3, rhat_target=0.0, num_warmup=30, kernel="hmc",
            num_leapfrog=4, seed=0, trace=tr,
        )
    events = read_trace(str(p))
    blocks = [e for e in events if e["event"] == "sample_block"]
    assert len(blocks) == 3
    for e in blocks:
        assert e["pipelined"] is True
        for k in ("t_host_hidden_s", "device_idle_s", "t_wait_s"):
            v = e[k]
            assert np.isfinite(v) and v >= 0.0, (k, e)
    s = summarize_trace(events)
    ov = s["overlap"]
    for k in ("t_host_hidden_s", "device_idle_s", "t_wait_s",
              "device_idle_frac"):
        assert np.isfinite(ov[k]) and ov[k] >= 0.0, (k, ov)
    assert ov["device_idle_frac"] <= 1.0, ov


def test_sync_idle_fraction_bounded_with_checkpoints(tmp_path):
    """Serial mode attributes the WHOLE host cycle (diagnostics +
    checkpoint fsyncs) as device idle; the summarized fraction must still
    land in [0, 1] — the denominator covers the checkpoint phase too."""
    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        stark_tpu.sample_until_converged(
            StdNormal2(), chains=2, block_size=10, max_blocks=4,
            min_blocks=4, rhat_target=0.0, num_warmup=20, kernel="hmc",
            num_leapfrog=4, seed=0, trace=tr, sync_blocks=True,
            checkpoint_path=str(tmp_path / "c.npz"),
        )
    ov = summarize_trace(read_trace(str(p)))["overlap"]
    assert 0.0 <= ov["device_idle_frac"] <= 1.0, ov
    assert ov["device_idle_s"] >= 0.0


def test_trace_report_renders_overlap(tmp_path):
    """tools/trace_report.py surfaces the device-idle fraction column."""
    import importlib.util
    import io
    from contextlib import redirect_stdout

    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        stark_tpu.sample_until_converged(
            StdNormal2(), chains=2, block_size=20, max_blocks=2,
            min_blocks=2, rhat_target=0.0, num_warmup=30, kernel="hmc",
            num_leapfrog=4, seed=0, trace=tr,
        )
    spec = importlib.util.spec_from_file_location(
        "trace_report",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "trace_report.py"),
    )
    trace_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_report)
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert trace_report.main([str(p)]) == 0
    out = buf.getvalue()
    assert "device idle fraction" in out
    assert "host work hidden" in out
    # --json carries the machine-readable overlap dict
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert trace_report.main([str(p), "--json"]) == 0
    summary = json.loads(buf.getvalue())
    assert "device_idle_frac" in summary["overlap"]


def test_drawstore_draw_major_append(tmp_path):
    """append(draw_major=True) writes the identical bytes the chain-major
    path does — the ensemble path's zero-transpose persistence."""
    rng = np.random.default_rng(0)
    blocks = [rng.standard_normal((3, 7, 2)).astype(np.float32)
              for _ in range(3)]
    p_cm = str(tmp_path / "cm.stkd")
    p_dm = str(tmp_path / "dm.stkd")
    with DrawStore(p_cm, chains=3, dim=2) as ds:
        for b in blocks:
            ds.append(b)
    with DrawStore(p_dm, chains=3, dim=2) as ds:
        for b in blocks:
            ds.append(np.ascontiguousarray(b.transpose(1, 0, 2)),
                      draw_major=True)
    with open(p_cm, "rb") as f:
        cm = f.read()
    with open(p_dm, "rb") as f:
        dm = f.read()
    assert cm == dm
    draws, _, _ = read_draws(p_dm)
    np.testing.assert_array_equal(
        draws, np.concatenate([b.transpose(1, 0, 2) for b in blocks])
    )
    # shape validation still fires in draw-major order
    with DrawStore(str(tmp_path / "v.stkd"), chains=3, dim=2) as ds:
        with pytest.raises(ValueError):
            ds.append(np.zeros((3, 7, 2), np.float32), draw_major=True)


def test_draw_history_matches_concatenate():
    """DrawHistory == np.concatenate semantics across growth boundaries,
    including the worst-k fancy-index subset."""
    rng = np.random.default_rng(1)
    hist = diagnostics.DrawHistory(2, 5)
    blocks = []
    for n in (3, 40, 7, 64, 1):
        b = rng.standard_normal((2, n, 5)).astype(np.float32)
        blocks.append(b)
        hist.append(b)
    ref = np.concatenate(blocks, axis=1)
    assert hist.rows == ref.shape[1] and len(hist) == ref.shape[1]
    np.testing.assert_array_equal(hist.view(), ref)
    cols = np.array([4, 0, 2])
    np.testing.assert_array_equal(hist.take(cols), ref[:, :, cols])
    with pytest.raises(ValueError):
        hist.append(np.zeros((2, 3, 4), np.float32))


def test_block_post_failpoint_fires_after_checkpoint(tmp_path):
    """runner.block.post crashes AFTER the block is durable: the
    checkpoint on disk accounts for the block that just completed."""
    ckpt = str(tmp_path / "c.npz")
    faults.reset()
    faults.configure("runner.block.post=crash*1")
    try:
        with pytest.raises(faults.InjectedFault):
            stark_tpu.sample_until_converged(
                StdNormal2(), chains=2, block_size=20, max_blocks=3,
                min_blocks=3, rhat_target=0.0, num_warmup=30, kernel="hmc",
                num_leapfrog=4, seed=0, checkpoint_path=ckpt,
            )
    finally:
        faults.reset()
    _, meta = load_checkpoint(ckpt)
    assert meta["blocks_done"] == 1


def test_compilation_cache_helper(tmp_path, monkeypatch):
    """enable_compilation_cache: workdir-keyed default, env precedence,
    STARK_COMPILE_CACHE override/disable."""
    import jax

    from stark_tpu.platform import enable_compilation_cache

    prev = jax.config.jax_compilation_cache_dir
    try:
        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
        monkeypatch.delenv("STARK_COMPILE_CACHE", raising=False)
        d = str(tmp_path / "cache")
        assert enable_compilation_cache(d) == d
        assert jax.config.jax_compilation_cache_dir == d
        assert os.path.isdir(d)
        # an env-configured cache always wins and is never overridden
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/env/cache")
        assert enable_compilation_cache(str(tmp_path / "x")) == "/env/cache"
        assert jax.config.jax_compilation_cache_dir == d  # untouched
        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR")
        # STARK_COMPILE_CACHE=0 disables library-level enabling
        monkeypatch.setenv("STARK_COMPILE_CACHE", "0")
        assert enable_compilation_cache(str(tmp_path / "y")) is None
        # ...and a path value redirects it
        override = str(tmp_path / "override")
        monkeypatch.setenv("STARK_COMPILE_CACHE", override)
        assert enable_compilation_cache(str(tmp_path / "z")) == override
        assert jax.config.jax_compilation_cache_dir == override
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)

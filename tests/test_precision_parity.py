"""tools/precision_parity.py zoo sweep: the fused-op x {f32, bf16} x
{default, high} parity grid passes at CPU-smoke scale, the tolerance
bands resolve as documented, and a genuinely broken op fails a cell.

The full-size sweep is the on-chip adoption gate; this tier-1 smoke
pins the harness (the reference sees the same rounded X, env knobs are
restored, every zoo op is registered) so an on-chip run can only fail
for numerics, not plumbing.
"""

import importlib
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(scope="module")
def parity(monkeypatch_module=None):
    # shrink the sweep before module constants are read at import time
    os.environ["PARITY_SWEEP_N"] = "1500"
    os.environ["PARITY_SWEEP_G"] = "30"
    os.environ["PARITY_SWEEP_D"] = "6"
    import precision_parity

    importlib.reload(precision_parity)
    yield precision_parity
    for k in ("PARITY_SWEEP_N", "PARITY_SWEEP_G", "PARITY_SWEEP_D"):
        os.environ.pop(k, None)


def test_zoo_cases_cover_every_fused_family(parity):
    names = {c[0] for c in parity.zoo_cases()}
    assert {
        "logistic", "hier_logistic", "hier_logistic_grouped", "gaussian",
        "glm_poisson", "lmm_offset", "lmm", "irt", "ordinal", "robust",
    } <= names


def test_band_resolution(parity):
    assert parity.band_for("f32", "high") == "tight"
    assert parity.band_for("bf16", "high") == "mid"
    assert parity.band_for("f32", "default") == "wide"
    assert parity.band_for("bf16", "default") == "wide"
    # quantized X columns: their own wide-band tier at EITHER precision
    for q in parity.QUANT_X_DTYPES:
        assert parity.band_for(q, "high") == "quant"
        assert parity.band_for(q, "default") == "quant"
    assert set(parity.X_DTYPES) == {
        "f32", "bf16", "int8", "fp8e4m3", "fp8e5m2"
    }


def test_full_sweep_passes(parity):
    """The whole grid at smoke scale — every op x {f32, bf16, int8,
    fp8e4m3, fp8e5m2} x {default, high} cell inside its band, and the
    env knobs restored afterwards."""
    prior_env = {
        k: os.environ.get(k)
        for k in ("STARK_FUSED_PRECISION", "STARK_FUSED_X_DTYPE",
                  "STARK_FUSED_LMM", "STARK_FUSED_IRT")
    }
    rows, ok = parity.run_sweep()
    assert ok, [r for r in rows if not r["ok"]]
    assert len(rows) == len(parity.zoo_cases()) * 2 * len(parity.X_DTYPES)
    for k, v in prior_env.items():
        assert os.environ.get(k) == v
    # the knob-gated ops actually exercised their fused path: parity
    # deltas must be nonzero somewhere (fused != reference computation)
    assert any(r["grad_rel"] > 0 for r in rows if r["op"] == "lmm")
    # quantized cells carry the calibration-quality artifact column for
    # every op that streams a design matrix; f32/bf16 cells never do
    for r in rows:
        if r["x_dtype"] in parity.QUANT_X_DTYPES and r["op"] != "irt":
            assert r["quant_col_err"] is not None and r["quant_col_err"] > 0
        else:
            assert r["quant_col_err"] is None
    # int8's uniform grid calibrates tighter than fp8e5m2's 2-bit
    # mantissa on the same gaussian columns
    err = {
        q: max(
            r["quant_col_err"] for r in rows
            if r["x_dtype"] == q and r["quant_col_err"] is not None
        )
        for q in ("int8", "fp8e5m2")
    }
    assert err["int8"] < err["fp8e5m2"]


def test_broken_op_fails_cell(parity):
    """A fused model whose likelihood deviates beyond the band must fail
    its cell — the gate can actually catch a broken kernel."""
    import jax

    from stark_tpu.models import Logistic, synth_logistic_data

    class BrokenFused(Logistic):
        def log_lik(self, p, data):
            return 1.01 * super().log_lik(p, data)  # 1% bias

    d = 4
    data, _ = synth_logistic_data(jax.random.PRNGKey(0), 500, d)
    row = parity.sweep_cell(
        "broken", Logistic(d), BrokenFused(d), data, None, "f32", "high"
    )
    assert not row["ok"]

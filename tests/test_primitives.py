"""`parallel.primitives` — the DrJAX-style MapReduce layer every
parallel composition (consensus, tempering, sharded backend, mesh fleet)
now runs on.

The contracts: the no-mesh fast path is literally ``jax.jit`` (bit- and
trace-identical to the hand-rolled code it replaced); the mesh path's
per-shard results equal the unsharded computation; `reduce_tree` is the
in-program psum/pmax/pmin with an axis-None identity; the placement
helpers land leaves on the requested shardings; `gather_tree` hands back
the global host view.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stark_tpu.parallel.mesh import make_mesh
from stark_tpu.parallel.primitives import (
    axis_size,
    broadcast,
    gather_tree,
    map_shards,
    reduce_tree,
    run_over_chains,
    shard_put,
)


def _mesh(n, axis="data"):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices (conftest forces 8)")
    return make_mesh({axis: n}, devices=jax.devices()[:n])


def test_identity_fast_path_is_plain_jit():
    """mesh=None returns exactly jit(fn): same results, and a jitted
    callable (lowering works) — the single-device callers' bit-identity
    rides on there being NO wrapper at all."""

    def f(x, y):
        return x * 2.0 + y

    jf = map_shards(f)
    x = jnp.arange(8.0)
    np.testing.assert_array_equal(np.asarray(jf(x, x)), np.asarray(x * 3.0))
    # a jit-wrapped callable exposes lower() — a plain wrapper would not
    assert hasattr(jf, "lower")


def test_map_shards_matches_unsharded():
    """Per-shard map over "data" == the unsharded vmap, bitwise."""
    mesh = _mesh(4)
    v = jax.vmap(lambda x: jnp.sin(x) * 2.0)
    x = jnp.arange(8.0).reshape(8, 1)
    ref = np.asarray(jax.jit(v)(x))
    out = map_shards(v, mesh=mesh, axis="data")(x)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_map_shards_explicit_mixed_specs():
    """Replicated args (P()) see the FULL value on every shard."""
    mesh = _mesh(2)

    def f(x, c):
        # c is replicated: every shard adds the same full-vector sum
        return x + jnp.sum(c)

    x = jnp.arange(4.0)
    c = jnp.asarray([1.0, 2.0])
    out = map_shards(
        f, mesh=mesh, in_specs=(P("data"), P()), out_specs=P("data")
    )(x, c)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x + 3.0))


def test_map_shards_needs_specs_or_axis():
    with pytest.raises(ValueError, match="axis"):
        map_shards(lambda x: x, mesh=_mesh(2))
    with pytest.raises(ValueError, match="arity"):
        map_shards(lambda *a: a[0], mesh=_mesh(2), axis="data")


def test_reduce_tree_psum_inside_map():
    """The reduce primitive: a psum over the mapped axis equals the
    global sum on every shard — the MapReduce composition."""
    mesh = _mesh(4)

    def f(x):
        return reduce_tree(jnp.sum(x), axis="data")

    x = jnp.arange(8.0)
    out = map_shards(
        f, mesh=mesh, in_specs=(P("data"),), out_specs=P()
    )(x)
    assert float(out) == float(jnp.sum(x))


def test_reduce_tree_identity_and_ops():
    tree = {"a": jnp.asarray([1.0, 2.0])}
    same = reduce_tree(tree, axis=None)
    assert same is tree  # axis=None: shared code runs unchanged
    with pytest.raises(ValueError, match="unknown reduce op"):
        reduce_tree(tree, axis="data", op="mean")


def test_shard_put_and_broadcast_place_leaves():
    mesh = _mesh(2)
    x = np.arange(4.0, dtype=np.float32)
    sharded = shard_put({"x": x}, mesh, P("data"))
    assert sharded["x"].sharding.spec == P("data")
    rep = broadcast({"c": np.float32(3.0)}, mesh)
    assert rep["c"].sharding.spec == P()
    # no mesh: both are the identity
    t = {"x": x}
    assert shard_put(t, None, P("data")) is t
    assert broadcast(t, None) is t


def test_gather_tree_global_host_view():
    mesh = _mesh(2)
    x = np.arange(4.0, dtype=np.float32)
    sharded = shard_put({"x": x}, mesh, P("data"))
    back = gather_tree(sharded)
    assert isinstance(back["x"], np.ndarray)
    np.testing.assert_array_equal(back["x"], x)


def test_axis_size():
    assert axis_size(None, "problems") == 1
    mesh = _mesh(4)
    assert axis_size(mesh, "data") == 4
    with pytest.raises(ValueError, match="no 'chains' axis"):
        axis_size(mesh, "chains")


def test_run_over_chains_parity():
    """The chains-axis dispatch helper (tempering / SG-HMC) returns the
    same values as the plain vmapped computation."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mesh = make_mesh(
        {"data": 1, "chains": 2}, devices=jax.devices()[:2]
    )
    v = jax.vmap(lambda k, z: (z * 2.0, jnp.sum(z)))
    keys = jnp.zeros((4, 2), jnp.uint32)
    z = jnp.arange(8.0).reshape(4, 2)
    ref = jax.jit(v)(keys, z)
    out = run_over_chains(mesh, v, keys, z)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(np.asarray(o), np.asarray(r))
    bad = make_mesh({"data": 2}, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="chains"):
        run_over_chains(bad, v, keys, z)

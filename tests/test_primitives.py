"""`parallel.primitives` — the DrJAX-style MapReduce layer every
parallel composition (consensus, tempering, sharded backend, mesh fleet)
now runs on.

The contracts: the no-mesh fast path is literally ``jax.jit`` (bit- and
trace-identical to the hand-rolled code it replaced); the mesh path's
per-shard results equal the unsharded computation; `reduce_tree` is the
in-program psum/pmax/pmin with an axis-None identity; the placement
helpers land leaves on the requested shardings; `gather_tree` hands back
the global host view.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from stark_tpu.parallel.mesh import make_mesh
from stark_tpu.parallel.primitives import (
    axis_size,
    broadcast,
    gather_tree,
    map_shards,
    reduce_tree,
    run_over_chains,
    shard_put,
)


def _mesh(n, axis="data"):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices (conftest forces 8)")
    return make_mesh({axis: n}, devices=jax.devices()[:n])


def test_identity_fast_path_is_plain_jit():
    """mesh=None returns exactly jit(fn): same results, and a jitted
    callable (lowering works) — the single-device callers' bit-identity
    rides on there being NO wrapper at all."""

    def f(x, y):
        return x * 2.0 + y

    jf = map_shards(f)
    x = jnp.arange(8.0)
    np.testing.assert_array_equal(np.asarray(jf(x, x)), np.asarray(x * 3.0))
    # a jit-wrapped callable exposes lower() — a plain wrapper would not
    assert hasattr(jf, "lower")


def test_map_shards_matches_unsharded():
    """Per-shard map over "data" == the unsharded vmap, bitwise."""
    mesh = _mesh(4)
    v = jax.vmap(lambda x: jnp.sin(x) * 2.0)
    x = jnp.arange(8.0).reshape(8, 1)
    ref = np.asarray(jax.jit(v)(x))
    out = map_shards(v, mesh=mesh, axis="data")(x)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_map_shards_explicit_mixed_specs():
    """Replicated args (P()) see the FULL value on every shard."""
    mesh = _mesh(2)

    def f(x, c):
        # c is replicated: every shard adds the same full-vector sum
        return x + jnp.sum(c)

    x = jnp.arange(4.0)
    c = jnp.asarray([1.0, 2.0])
    out = map_shards(
        f, mesh=mesh, in_specs=(P("data"), P()), out_specs=P("data")
    )(x, c)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x + 3.0))


def test_map_shards_needs_specs_or_axis():
    with pytest.raises(ValueError, match="axis"):
        map_shards(lambda x: x, mesh=_mesh(2))
    with pytest.raises(ValueError, match="arity"):
        map_shards(lambda *a: a[0], mesh=_mesh(2), axis="data")


def test_reduce_tree_psum_inside_map():
    """The reduce primitive: a psum over the mapped axis equals the
    global sum on every shard — the MapReduce composition."""
    mesh = _mesh(4)

    def f(x):
        return reduce_tree(jnp.sum(x), axis="data")

    x = jnp.arange(8.0)
    out = map_shards(
        f, mesh=mesh, in_specs=(P("data"),), out_specs=P()
    )(x)
    assert float(out) == float(jnp.sum(x))


def test_reduce_tree_identity_and_ops():
    tree = {"a": jnp.asarray([1.0, 2.0])}
    same = reduce_tree(tree, axis=None)
    assert same is tree  # axis=None: shared code runs unchanged
    with pytest.raises(ValueError, match="unknown reduce op"):
        reduce_tree(tree, axis="data", op="mean")


def test_shard_put_and_broadcast_place_leaves():
    mesh = _mesh(2)
    x = np.arange(4.0, dtype=np.float32)
    sharded = shard_put({"x": x}, mesh, P("data"))
    assert sharded["x"].sharding.spec == P("data")
    rep = broadcast({"c": np.float32(3.0)}, mesh)
    assert rep["c"].sharding.spec == P()
    # no mesh: both are the identity
    t = {"x": x}
    assert shard_put(t, None, P("data")) is t
    assert broadcast(t, None) is t


def test_gather_tree_global_host_view():
    mesh = _mesh(2)
    x = np.arange(4.0, dtype=np.float32)
    sharded = shard_put({"x": x}, mesh, P("data"))
    back = gather_tree(sharded)
    assert isinstance(back["x"], np.ndarray)
    np.testing.assert_array_equal(back["x"], x)


def test_axis_size():
    assert axis_size(None, "problems") == 1
    mesh = _mesh(4)
    assert axis_size(mesh, "data") == 4
    with pytest.raises(ValueError, match="no 'chains' axis"):
        axis_size(mesh, "chains")


def test_run_over_chains_parity():
    """The chains-axis dispatch helper (tempering / SG-HMC) returns the
    same values as the plain vmapped computation."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mesh = make_mesh(
        {"data": 1, "chains": 2}, devices=jax.devices()[:2]
    )
    v = jax.vmap(lambda k, z: (z * 2.0, jnp.sum(z)))
    keys = jnp.zeros((4, 2), jnp.uint32)
    z = jnp.arange(8.0).reshape(4, 2)
    ref = jax.jit(v)(keys, z)
    out = run_over_chains(mesh, v, keys, z)
    for r, o in zip(ref, out):
        np.testing.assert_array_equal(np.asarray(o), np.asarray(r))
    bad = make_mesh({"data": 2}, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="chains"):
        run_over_chains(bad, v, keys, z)


# ---------------------------------------------------------------------------
# scan_shards — the ordered cross-shard scan (PR 19)
# ---------------------------------------------------------------------------


def _exclusive_sums(shard_sums, reverse=False):
    out = []
    for i in range(len(shard_sums)):
        peers = shard_sums[i + 1:] if reverse else shard_sums[:i]
        out.append(float(sum(peers)))
    return out


def test_scan_shards_gather_forward_and_reverse():
    """Gather mode hands ``combine`` the shard-ordered totals and the
    strictly-before mask (strictly-after under ``reverse``) — the
    masked-sum combine reproduces the exclusive prefix per shard."""
    from stark_tpu.compat import shard_map
    from stark_tpu.parallel.primitives import scan_shards

    mesh = _mesh(4)
    x = jnp.arange(8.0)  # shard sums: [1, 5, 9, 13]

    def run(reverse):
        def f(xs):
            c = scan_shards(
                jnp.sum(xs), "data", reverse=reverse,
                combine=lambda t, m: jnp.sum(jnp.where(m, t, 0.0)),
            )
            return c[None]

        fn = shard_map(f, mesh=mesh, in_specs=(P("data"),),
                       out_specs=P("data"), check_vma=False)
        return np.asarray(jax.jit(fn)(x))

    np.testing.assert_array_equal(
        run(False), _exclusive_sums([1.0, 5.0, 9.0, 13.0])
    )
    np.testing.assert_array_equal(
        run(True), _exclusive_sums([1.0, 5.0, 9.0, 13.0], reverse=True)
    )


def test_scan_shards_axis_none_identity():
    """axis=None is the single-shard case: one stacked total, an
    all-False mask (no predecessors in either direction)."""
    from stark_tpu.parallel.primitives import scan_shards

    def combine(totals, mask):
        assert totals.shape == (1,)
        return jnp.sum(jnp.where(mask, totals, 0.0))

    assert float(scan_shards(jnp.float32(7.0), None, combine=combine)) == 0.0
    v = jnp.arange(6.0)
    np.testing.assert_array_equal(
        np.asarray(scan_shards(v, None, replicated=True)), np.asarray(v)
    )


def test_scan_shards_replicated_ordered_slices():
    """Replicated mode returns shard s's contiguous slice of the full
    replicated sequence — gathering the per-shard slices along the shard
    axis reassembles the sequence exactly."""
    from stark_tpu.compat import shard_map
    from stark_tpu.parallel.primitives import scan_shards

    mesh = _mesh(4)
    full = jnp.arange(8.0) * 1.5

    def f(_):
        return scan_shards(full, "data", replicated=True)

    fn = shard_map(f, mesh=mesh, in_specs=(P("data"),),
                   out_specs=P("data"), check_vma=False)
    out = jax.jit(fn)(jnp.zeros(4))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(full))


def test_scan_shards_mode_and_divisibility_errors():
    from stark_tpu.compat import shard_map
    from stark_tpu.parallel.primitives import scan_shards

    with pytest.raises(ValueError, match="combine"):
        scan_shards(jnp.zeros(2), None)  # gather mode needs combine=
    with pytest.raises(ValueError, match="gather mode"):
        scan_shards(jnp.zeros(2), None, replicated=True,
                    combine=lambda t, m: t)
    mesh = _mesh(4)
    fn = shard_map(
        lambda _: scan_shards(jnp.zeros(7), "data", replicated=True),
        mesh=mesh, in_specs=(P("data"),), out_specs=P("data"),
        check_vma=False,
    )
    with pytest.raises(ValueError, match="does not divide"):
        jax.jit(fn)(jnp.zeros(4))  # 7 rows over 4 shards would alias


def test_scan_shards_comm_accounted_and_silenceable(tmp_path, monkeypatch):
    """Gather mode emits one comm event per traced scan (wire = payload
    x axis size — the allgather); replicated mode moves nothing and
    emits nothing; STARK_COMM_TELEMETRY=0 silences the accounting with
    bit-identical results."""
    from stark_tpu.compat import shard_map
    from stark_tpu.parallel.primitives import scan_shards
    from stark_tpu.telemetry import RunTrace, read_trace, use_trace

    mesh = _mesh(4)
    x = jnp.arange(8.0)

    def compute():
        def f(xs):
            c = scan_shards(
                jnp.sum(xs), "data",
                combine=lambda t, m: jnp.sum(jnp.where(m, t, 0.0)),
            )
            h = scan_shards(jnp.arange(8.0), "data", replicated=True)
            return c + h

        fn = shard_map(f, mesh=mesh, in_specs=(P("data"),),
                       out_specs=P("data"), check_vma=False)
        return np.asarray(jax.jit(fn)(x))

    trace_on = str(tmp_path / "on.jsonl")
    with RunTrace(trace_on) as tr, use_trace(tr):
        y_on = compute()
    comm = [e for e in read_trace(trace_on) if e.get("event") == "comm"]
    scans = [e for e in comm if e["primitive"] == "scan_shards"]
    assert len(scans) == 1, comm  # replicated half emits nothing
    (ev,) = scans
    assert ev["axis"] == "data" and ev["participants"] == 4
    assert ev["payload_bytes"] == 4          # one f32 scalar per shard
    assert ev["wire_bytes"] == 16            # allgather: payload x shards

    monkeypatch.setenv("STARK_COMM_TELEMETRY", "0")
    trace_off = str(tmp_path / "off.jsonl")
    with RunTrace(trace_off) as tr, use_trace(tr):
        y_off = compute()
    assert not [e for e in read_trace(trace_off)
                if e.get("event") == "comm"]
    np.testing.assert_array_equal(y_on, y_off)

"""Run timeline profiler (stark_tpu/profiling.py): span attribution,
the ``span`` event family, and the promoted dispatch-count probe.

The acceptance contract under test: a fresh eight-schools trace must
decompose into non-overlapping spans covering >=95% of the run wall
(``tools/timeline_report.py``), ``span`` is a registered event type
(schema lint green), pre-PR-11 traces degrade to ``n/a`` — never an
error — and `profiling.DispatchProbe` is the PR 8 `_GradEvalProbe`
promoted (same counting semantics, re-exported under the old name for
the nutssched microbench).
"""

import json
import os
import sys

import pytest

from stark_tpu import profiling, telemetry
from stark_tpu.profiling import (
    DispatchProbe,
    SpanRecorder,
    deregister_probe,
    probe_counts,
    register_probe,
    spans_from_events,
    timeline_summary,
)

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
)


def _ev(event, wall_s, run=1, **fields):
    return {"schema": 1, "event": event, "ts": 0.0, "wall_s": wall_s,
            "run": run, **fields}


def _synthetic_trace():
    """A hand-built run: compile 1s, warmup 2s, two draw blocks (one
    with overlap fields), one checkpoint, collect — tiling 10s."""
    return [
        _ev("run_start", 0.0, model="M", kernel="nuts", chains=2),
        _ev("compile", 1.0, dur_s=1.0, stage="build"),
        _ev("warmup_block", 3.0, dur_s=2.0),
        # block 1: 2s, 0.5s host hidden + 0.25s device idle
        _ev("sample_block", 5.0, dur_s=2.0, block=1,
            t_host_hidden_s=0.5, device_idle_s=0.25),
        _ev("checkpoint", 5.5, dur_s=0.5, block=1),
        # block 2: no overlap fields (pre-PR-3 shape) -> one dispatch span
        _ev("sample_block", 8.5, dur_s=3.0, block=2),
        _ev("collect", 10.0, dur_s=1.5),
        _ev("run_end", 10.0, dur_s=10.0, converged=True),
    ]


# ---------------------------------------------------------------------------
# span synthesis
# ---------------------------------------------------------------------------


def test_spans_tile_and_never_overlap():
    tl = spans_from_events(_synthetic_trace())
    assert tl["synthesized"] is True
    assert tl["wall_s"] == pytest.approx(10.0)
    spans = tl["spans"]
    # strictly non-overlapping, sorted
    for a, b in zip(spans, spans[1:]):
        assert a["end"] <= b["start"] + 1e-9
    covered = sum(sp["dur"] for sp in spans)
    assert covered == pytest.approx(10.0, abs=1e-6)
    kinds = {sp["kind"] for sp in spans}
    assert {"compile", "warmup", "dispatch", "host_hidden",
            "device_idle", "checkpoint", "host"} == kinds


def test_block_overlap_decomposition_sums_to_block_wall():
    spans = [
        sp for sp in spans_from_events(_synthetic_trace())["spans"]
        if sp.get("block") == 1 and sp["src"] == "sample_block"
    ]
    by_kind = {sp["kind"]: sp["dur"] for sp in spans}
    assert by_kind["host_hidden"] == pytest.approx(0.5)
    assert by_kind["device_idle"] == pytest.approx(0.25)
    assert by_kind["dispatch"] == pytest.approx(1.25)
    assert sum(by_kind.values()) == pytest.approx(2.0)


def test_nested_phase_keeps_inner_attribution():
    """The fleet nests warmup_block phases inside a compile setup phase:
    the inner (earlier-emitted) spans keep their interval, the outer
    keeps only the unclaimed remainder — no double counting."""
    events = [
        _ev("run_start", 0.0),
        _ev("warmup_block", 2.0, dur_s=1.0),   # inner [1, 2]
        _ev("compile", 3.0, dur_s=3.0),        # outer [0, 3]
        _ev("run_end", 3.0, dur_s=3.0),
    ]
    tl = spans_from_events(events)
    by_kind = {}
    for sp in tl["spans"]:
        by_kind[sp["kind"]] = by_kind.get(sp["kind"], 0.0) + sp["dur"]
    assert by_kind["warmup"] == pytest.approx(1.0)
    assert by_kind["compile"] == pytest.approx(2.0)  # [0,1] + [2,3]
    assert sum(by_kind.values()) == pytest.approx(3.0)


def test_overlap_estimates_clipped_to_block_wall():
    """An overshooting device-idle estimate can never attribute more
    time than the block's own measured wall."""
    events = [
        _ev("run_start", 0.0),
        _ev("sample_block", 1.0, dur_s=1.0, block=1,
            t_host_hidden_s=2.0, device_idle_s=2.0),
        _ev("run_end", 1.0, dur_s=1.0),
    ]
    spans = spans_from_events(events)["spans"]
    assert sum(sp["dur"] for sp in spans) == pytest.approx(1.0)


def test_summary_fields_and_null_conventions():
    s = timeline_summary(_synthetic_trace())
    assert s["compile_s"] == pytest.approx(1.0)
    assert s["dispatch_count"] == 3  # warmup + 2 draw blocks
    assert s["span_coverage_frac"] == pytest.approx(1.0)
    # a trace with no phase events: every field null, never 0.0
    bare = timeline_summary([_ev("run_start", 0.0), _ev("run_end", 1.0)])
    assert bare["compile_s"] is None
    assert bare["dispatch_count"] is None
    assert bare["span_coverage_frac"] is None
    empty = timeline_summary([])
    assert empty["span_coverage_frac"] is None


def test_summary_picks_last_run_by_default():
    events = _synthetic_trace() + [
        _ev("run_start", 11.0, run=2),
        _ev("compile", 13.0, run=2, dur_s=2.0),
        _ev("run_end", 13.0, run=2, dur_s=2.0),
    ]
    s = timeline_summary(events)
    assert s["run"] == 2
    assert s["compile_s"] == pytest.approx(2.0)
    assert timeline_summary(events, run=1)["dispatch_count"] == 3


# ---------------------------------------------------------------------------
# span event family (SpanRecorder)
# ---------------------------------------------------------------------------


def test_span_event_registered_in_schema():
    assert "span" in telemetry.ALL_EVENT_TYPES
    assert "span" in telemetry.PROFILING_EVENT_TYPES


def test_span_recorder_emits_literal_span_events(tmp_path):
    path = str(tmp_path / "t.jsonl")
    # no run_start here: these synthetic dur_s values predate the trace
    # clock, and the run window would (correctly) clip them — the span
    # content is what's under test
    with telemetry.RunTrace(path) as tr:
        rec = SpanRecorder(tr).install()
        try:
            tr.emit("sample_block", dur_s=2.0, block=1,
                    t_host_hidden_s=0.5, device_idle_s=0.25)
        finally:
            rec.uninstall()
        tr.emit("checkpoint", dur_s=0.1)  # after uninstall: no span
    events = telemetry.read_trace(path)
    spans = [e for e in events if e["event"] == "span"]
    assert {e["kind"] for e in spans} == {"dispatch", "host_hidden",
                                          "device_idle"}
    for e in spans:
        assert e["src"] == "sample_block"
        assert e["end_s"] - e["start_s"] == pytest.approx(e["dur_s"],
                                                          abs=1e-3)
        telemetry.validate_event(e)
    assert not any(
        e["event"] == "span" and e.get("src") == "checkpoint"
        for e in events
    )
    # the read path prefers literal spans over synthesis
    tl = spans_from_events(events)
    assert tl["synthesized"] is False
    assert {sp["kind"] for sp in tl["spans"]} == {"dispatch",
                                                  "host_hidden",
                                                  "device_idle"}


def test_span_recorder_gap_attribution_matches_synthesis(tmp_path):
    """Turning the recorder ON must not lower coverage: the literal
    span stream carries the same block-loop gap attribution the
    synthesized read path applies (the pipelined runner's out-of-line
    enqueue wall)."""
    # pipelined shape: block 2's [end-dur, end] leaves a gap after
    # block 1 (its enqueue ran while block 1 computed)
    phase_events = [
        ("sample_block", dict(dur_s=1.0, block=1)),
        ("sample_block", dict(dur_s=1.0, block=2)),
    ]
    path = str(tmp_path / "t.jsonl")
    import time as _time

    with telemetry.RunTrace(path) as tr:
        rec = SpanRecorder(tr).install()
        try:
            for ev, fields in phase_events:
                _time.sleep(1.2)  # real wall gap between completions
                tr.emit(ev, **fields)
        finally:
            rec.uninstall()
    events = telemetry.read_trace(path)
    literal = spans_from_events(events)
    assert literal["synthesized"] is False
    gap_spans = [sp for sp in literal["spans"] if sp.get("gap")]
    assert gap_spans and gap_spans[0]["kind"] == "dispatch"
    # the literal timeline covers the inter-block wall like the
    # synthesized one would
    synth = spans_from_events(
        [e for e in events if e["event"] != "span"]
    )
    lit_cov = sum(sp["dur"] for sp in literal["spans"])
    syn_cov = sum(sp["dur"] for sp in synth["spans"])
    assert lit_cov == pytest.approx(syn_cov, rel=0.05)


def test_maybe_record_spans_env_gate(tmp_path, monkeypatch):
    monkeypatch.delenv("STARK_PROFILE_SPANS", raising=False)
    with telemetry.RunTrace(str(tmp_path / "a.jsonl")) as tr:
        assert profiling.maybe_record_spans(tr) is None
    monkeypatch.setenv("STARK_PROFILE_SPANS", "1")
    assert profiling.maybe_record_spans(telemetry.NULL_TRACE) is None
    with telemetry.RunTrace(str(tmp_path / "b.jsonl")) as tr:
        rec = profiling.maybe_record_spans(tr)
        assert rec is not None
        rec.uninstall()
    assert not telemetry._EVENT_LISTENERS


# ---------------------------------------------------------------------------
# dispatch probe (promoted _GradEvalProbe)
# ---------------------------------------------------------------------------


def test_dispatch_probe_counts_executed_calls():
    import jax
    import jax.numpy as jnp

    probe = DispatchProbe(label="unit")
    f = jax.jit(probe.wrap(lambda x: x * 2.0))
    for _ in range(3):
        jax.block_until_ready(f(jnp.ones(4)))
    assert probe.snapshot() == 3
    probe.reset()
    assert probe.snapshot() == 0


def test_dispatch_probe_counts_masked_lane_evals_too():
    """The probe's reason to exist: a while_loop iteration evaluates
    every lane, finished or not — executed counts exceed 'useful'."""
    import jax
    import jax.numpy as jnp

    probe = DispatchProbe(label="loop")
    g = probe.wrap(lambda x: x + 1.0)

    @jax.jit
    def run(x):
        return jax.lax.fori_loop(0, 5, lambda i, v: g(v), x)

    jax.block_until_ready(run(jnp.zeros(2)))
    assert probe.snapshot() == 5


def test_probe_registry_roundtrip():
    probe = register_probe(DispatchProbe(label="reg_demo"))
    try:
        assert probe_counts(drain=False)["reg_demo"] == 0
        probe.calls = 7
        assert probe_counts()["reg_demo"] == 7
    finally:
        deregister_probe("reg_demo")
    assert "reg_demo" not in probe_counts(drain=False)


def test_benchmarks_reexports_probe_under_historical_name():
    from stark_tpu.benchmarks import _GradEvalProbe

    assert _GradEvalProbe is DispatchProbe


def test_probe_bind_matches_model_potential():
    """The FlatModel-compatible bind: same values/grads as the unprobed
    potential, calls counted per executed evaluation."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from stark_tpu.model import flatten_model, prepare_model_data
    from stark_tpu.models import Logistic, synth_logistic_data

    model = Logistic(num_features=3)
    data, _ = synth_logistic_data(jax.random.PRNGKey(0), 64, 3)
    fm = flatten_model(model)
    pdata = prepare_model_data(model, data)
    probe = DispatchProbe(fm)
    z = 0.1 * jnp.ones(fm.ndim)
    v_ref, g_ref = fm.bind(pdata).value_and_grad(z)
    pot = probe.bind(pdata)
    v, g = jax.jit(pot.value_and_grad)(z)
    jax.block_until_ready((v, g))
    np.testing.assert_allclose(np.asarray(v), np.asarray(v_ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-6)
    assert probe.snapshot() >= 1


# ---------------------------------------------------------------------------
# timeline_report tool + the eight-schools coverage acceptance
# ---------------------------------------------------------------------------


def _timeline_report_main():
    import timeline_report

    return timeline_report.main


def test_timeline_report_json_on_synthetic(tmp_path, capsys):
    path = tmp_path / "t.jsonl"
    with open(path, "w") as f:
        for e in _synthetic_trace():
            f.write(json.dumps(e) + "\n")
    assert _timeline_report_main()([str(path), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["span_coverage_frac"] == pytest.approx(1.0)
    assert out["dispatch_count"] == 3
    assert _timeline_report_main()([str(path), "--spans"]) == 0
    assert "dispatch" in capsys.readouterr().out


def test_timeline_report_na_safe_on_pre_pr11_trace(tmp_path, capsys):
    """A PR-1-era trace shape (no overlap fields, no collect, no
    run_end dur): renders n/a where it can't attribute, never raises."""
    path = tmp_path / "old.jsonl"
    events = [
        _ev("run_start", 0.0, model="M"),
        _ev("sample_block", 1.0, dur_s=1.0, block=1),
        _ev("chain_health", 1.1, max_rhat=1.01),
    ]
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    assert _timeline_report_main()([str(path)]) == 0
    out = capsys.readouterr().out
    assert "dispatch" in out
    # and an event-free run renders the no-spans note
    path2 = tmp_path / "bare.jsonl"
    with open(path2, "w") as f:
        f.write(json.dumps(_ev("run_start", 0.0)) + "\n")
    assert _timeline_report_main()([str(path2)]) == 0
    assert "n/a" in capsys.readouterr().out


def test_timeline_report_missing_or_empty_file_fails_cleanly(tmp_path):
    # missing file: exit 1 with a message, not a traceback
    assert _timeline_report_main()([str(tmp_path / "absent.jsonl")]) == 1
    (tmp_path / "empty.jsonl").write_text("")
    assert _timeline_report_main()([str(tmp_path / "empty.jsonl")]) == 1


def test_eight_schools_trace_coverage_at_least_95pct(tmp_path, capsys):
    """The acceptance criterion: a fresh eight-schools trace attributes
    >=95% of the run wall to non-overlapping spans."""
    from stark_tpu.models.eight_schools import EightSchools, eight_schools_data
    from stark_tpu.runner import sample_until_converged

    path = str(tmp_path / "es.jsonl")
    with telemetry.use_trace(telemetry.RunTrace(path)) as tr:
        sample_until_converged(
            EightSchools(), eight_schools_data(),
            chains=2, block_size=50, max_blocks=4, min_blocks=2,
            rhat_target=10.0, ess_target=1.0, num_warmup=100,
            kernel="hmc", num_leapfrog=8, seed=0,
        )
        tr.close()
    events = telemetry.read_trace(path)
    s = timeline_summary(events)
    assert s["span_coverage_frac"] is not None
    assert s["span_coverage_frac"] >= 0.95, s
    assert s["compile_s"] is not None and s["compile_s"] > 0
    assert s["dispatch_count"] is not None and s["dispatch_count"] >= 3
    # spans are non-overlapping by construction — verify on real data
    spans = spans_from_events(events)["spans"]
    for a, b in zip(spans, spans[1:]):
        assert a["end"] <= b["start"] + 1e-9
    # and the report renders it
    assert _timeline_report_main()([path]) == 0
    out = capsys.readouterr().out
    assert "attributed" in out and "compile" in out

"""The quantized data-plane (ops/quantize.py + the STARK_FUSED_X_DTYPE
int8/fp8 ladder): calibration/packing determinism, epilogue-folded
dequant dots, zoo parity against the dequantized-X reference, knob-off
bit-identity, the knob-flip lifecycle (packed data keeps working after
either knob flips), fleet stacking over quant-prepared data, sharding
row axes for the scale vector, and the bytes-accounting / telemetry
tags.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import stark_tpu
from stark_tpu import telemetry
from stark_tpu.model import flatten_model, prepare_model_data
from stark_tpu.models import (
    FusedIRT2PL,
    FusedLMM,
    FusedLogistic,
    FusedPoissonRegression,
    IRT2PL,
    LinearMixedModel,
    Logistic,
    PoissonRegression,
    synth_irt_data,
    synth_lmm_data,
    synth_logistic_data,
    synth_poisson_data,
)
from stark_tpu.ops import quantize
from stark_tpu.ops.precision import (
    X_DTYPE_NAMES,
    quant_percentile,
    x_stream_config,
    x_stream_dtype,
)

KEY = jax.random.PRNGKey(0)
QUANT_NAMES = ("int8", "fp8e4m3", "fp8e5m2")


# --- the dtype knob + error-message contract (the README/message pair
# once drifted: both now derive from X_DTYPE_NAMES) -------------------


def test_x_dtype_error_enumerates_exactly_the_accepted_set(monkeypatch):
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "f16")
    with pytest.raises(ValueError) as e:
        x_stream_dtype()
    msg = str(e.value)
    # the message's enumerated set IS the canonical tuple — no more, no
    # less — so the next dtype addition can't drift them apart again
    listed = msg.split("use ")[-1].split("|")
    assert tuple(listed) == X_DTYPE_NAMES
    for name in X_DTYPE_NAMES:
        monkeypatch.setenv("STARK_FUSED_X_DTYPE", name)
        x_stream_dtype()  # every advertised name resolves


def test_readme_documents_every_accepted_dtype():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    readme = open(os.path.join(repo, "README.md")).read()
    for name in X_DTYPE_NAMES:
        assert name in readme, (
            f"README must document STARK_FUSED_X_DTYPE={name} (the table "
            "and the resolver error message update together)"
        )


def test_quant_pct_knob_validation(monkeypatch):
    monkeypatch.delenv("STARK_QUANT_PCT", raising=False)
    assert quant_percentile() is None
    monkeypatch.setenv("STARK_QUANT_PCT", "99.5")
    assert quant_percentile() == 99.5
    monkeypatch.setenv("STARK_QUANT_PCT", "100")
    assert quant_percentile() is None  # 100th pct == absmax
    for bad in ("0", "-1", "101", "abc"):
        monkeypatch.setenv("STARK_QUANT_PCT", bad)
        with pytest.raises(ValueError):
            quant_percentile()


def test_x_stream_config_keys_on_quant_config(monkeypatch):
    monkeypatch.delenv("STARK_FUSED_X_DTYPE", raising=False)
    monkeypatch.delenv("STARK_QUANT_PCT", raising=False)
    assert x_stream_config() == "f32"
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "int8")
    assert x_stream_config() == "int8"
    monkeypatch.setenv("STARK_QUANT_PCT", "99.9")
    assert x_stream_config() == "int8@p99.9"
    # the pct only keys quantized configs (it has no effect elsewhere)
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "bf16")
    assert x_stream_config() == "bf16"


def test_quant_config_flip_retraces(monkeypatch):
    """Flipping STARK_QUANT_PCT mid-process must retrace the fused jits
    (the resolved quant config is in the cache key), mirroring the
    ADVICE-r5 precision-knob contract."""
    from stark_tpu.ops.ordinal_fused import (
        ordinal_loglik_value_and_grad as vg,
    )

    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "int8")
    monkeypatch.delenv("STARK_QUANT_PCT", raising=False)
    x = jax.random.normal(KEY, (64, 4))
    q, s = quantize.pack_slab(x.T, jnp.int8)
    y = jnp.zeros((64,))
    beta, cuts = jnp.zeros((4,)), jnp.linspace(-1.0, 1.0, 3)
    vg(beta, cuts, (q, s), y)
    before = vg._jit._cache_size()
    monkeypatch.setenv("STARK_QUANT_PCT", "99.0")
    vg(beta, cuts, (q, s), y)
    assert vg._jit._cache_size() == before + 1  # new static key


# --- calibration + packing ------------------------------------------


@pytest.mark.parametrize("name", QUANT_NAMES)
def test_pack_roundtrip_error_bounds_and_determinism(name):
    dtype = quantize.PACKED_DTYPES[name]
    x = jax.random.normal(KEY, (6, 500)) * jnp.array(
        [[0.01], [1.0], [100.0], [1e-4], [3.0], [0.0]]  # mixed col scales
    )
    q, s = quantize.pack_slab(x, dtype)
    assert q.shape == x.shape and q.dtype == jnp.dtype(dtype)
    assert s.shape == (6,) and s.dtype == jnp.float32
    xq = quantize.dequant(q, s)
    # per-row (per design-column) relative error bounded by the dtype's
    # resolution; the all-zero row is exact with scale 1.0
    err = np.max(np.abs(np.asarray(x - xq)), axis=1)
    amax = np.max(np.abs(np.asarray(x)), axis=1)
    bound = {"int8": 1.0 / 127, "fp8e4m3": 1.0 / 8, "fp8e5m2": 1.0 / 2}[name]
    live = amax > 0
    assert np.all(err[live] <= bound * amax[live] + 1e-12)
    assert float(s[5]) == 1.0 and not np.any(np.asarray(xq[5]))
    # determinism: identical bytes on a repack
    q2, s2 = quantize.pack_slab(x, dtype)
    assert np.asarray(q).tobytes() == np.asarray(q2).tobytes()
    assert np.asarray(s).tobytes() == np.asarray(s2).tobytes()


def test_percentile_calibration_clips_outliers(monkeypatch):
    """STARK_QUANT_PCT spends the packed range on the bulk: the scale
    shrinks to the percentile and the outlier clips to the band edge."""
    x = jnp.concatenate([jnp.linspace(-1, 1, 999), jnp.array([1000.0])])
    x = x[None, :]
    q_abs, s_abs = quantize.pack_slab(x, jnp.int8)
    monkeypatch.setenv("STARK_QUANT_PCT", "99.0")
    q_pct, s_pct = quantize.pack_slab(x, jnp.int8)
    assert float(s_pct[0]) < float(s_abs[0])  # bulk resolution recovered
    xq = quantize.dequant(q_pct, s_pct)
    # the outlier clipped to the top of the band...
    assert float(xq[0, -1]) == pytest.approx(127 * float(s_pct[0]))
    # ...and the bulk is far more accurate than under absmax
    bulk_err_pct = float(jnp.max(jnp.abs(xq[0, :-1] - x[0, :-1])))
    bulk_err_abs = float(
        jnp.max(jnp.abs(quantize.dequant(q_abs, s_abs)[0, :-1] - x[0, :-1]))
    )
    assert bulk_err_pct < bulk_err_abs / 50


def test_percentile_calibration_survives_sparse_columns(monkeypatch):
    """A mostly-zero column whose pct-th absolute percentile is exactly
    0 must fall back to absmax calibration — a zero percentile carries
    no information, and calibrating on it would zero the entire column
    (invisibly to the parity gate, which sees the same rounded X)."""
    monkeypatch.setenv("STARK_QUANT_PCT", "99.0")
    x = jnp.zeros((1, 1000)).at[0, :5].set(0.4)  # 99.5% zeros
    q, s = quantize.pack_slab(x, jnp.int8)
    xq = quantize.dequant(q, s)
    np.testing.assert_allclose(
        np.asarray(xq[0, :5]), 0.4, rtol=1.0 / 127
    )
    # and the all-zero-column fallback is untouched
    q0, s0 = quantize.pack_slab(jnp.zeros((1, 100)), jnp.int8)
    assert float(s0[0]) == 1.0 and not np.any(np.asarray(q0))


def test_dequant_dot_epilogue_matches_materialized():
    x = jax.random.normal(KEY, (8, 300))
    q, s = quantize.pack_slab(x, jnp.int8)
    xq = quantize.dequant(q, s)
    beta = jax.random.normal(jax.random.PRNGKey(1), (8,))
    resid = jax.random.normal(jax.random.PRNGKey(2), (300,))
    # forward: scaled axis contracted -> scales fold into beta
    np.testing.assert_allclose(
        np.asarray(quantize.dequant_dot(beta, (q, s))),
        np.asarray(jnp.dot(beta, xq)),
        rtol=1e-5, atol=1e-5,
    )
    # backward: scaled axis survives -> scales fold into the output
    np.testing.assert_allclose(
        np.asarray(quantize.dequant_dot((q, s), resid)),
        np.asarray(jnp.dot(xq, resid)),
        rtol=1e-5, atol=1e-5,
    )
    # plain arrays pass through bit-identically to the historical path
    f32 = x.astype(jnp.float32)
    assert (
        np.asarray(quantize.dequant_dot(beta, f32)).tobytes()
        == np.asarray(jnp.dot(beta, f32)).tobytes()
    )
    with pytest.raises(ValueError):
        quantize.dequant_dot((q, s), (q, s))


# --- knob-off bit-identity + lifecycle ------------------------------


def test_knob_off_prepare_is_bit_identical():
    """STARK_FUSED_X_DTYPE unset: prepare emits the historical f32 xT,
    no scale key — packed layout appears ONLY under the quant knob."""
    assert os.environ.get("STARK_FUSED_X_DTYPE") is None
    data, _ = synth_logistic_data(KEY, 200, 4)
    df = prepare_model_data(FusedLogistic(4), data)
    assert "xT_scale" not in df
    assert df["xT"].dtype == jnp.float32
    assert (
        np.asarray(df["xT"]).tobytes()
        == np.asarray(jnp.asarray(data["x"]).T).tobytes()
    )


@pytest.mark.parametrize("name", ("int8", "fp8e4m3"))
def test_fused_matches_dequantized_reference(name, monkeypatch):
    """The rounded-X convention at f32 tolerance: the fused path on the
    packed slab equals autodiff on the SAME dequantized matrix."""
    data, _ = synth_lmm_data(KEY, 500, 5, 30)
    monkeypatch.setenv("STARK_FUSED_LMM", "1")
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", name)
    fused = FusedLMM(5, 30)
    fm_f = flatten_model(fused)
    df = prepare_model_data(fused, data)
    assert df["xT"].dtype == jnp.dtype(quantize.PACKED_DTYPES[name])
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "f32")
    plain = LinearMixedModel(5, 30)
    fm_p = flatten_model(plain)
    dp = prepare_model_data(
        plain, {**data, "x": quantize.fake_quant(data["x"], name)}
    )
    z = 0.3 * jax.random.normal(jax.random.PRNGKey(5), (fm_p.ndim,))
    vp, gp = fm_p.potential_and_grad(z, dp)
    vf, gf = fm_f.potential_and_grad(z, df)
    np.testing.assert_allclose(vp, vf, rtol=1e-5, atol=1e-4)
    scale = float(jnp.max(jnp.abs(gp))) + 1e-6
    np.testing.assert_allclose(
        np.asarray(gf) / scale, np.asarray(gp) / scale,
        rtol=1e-4, atol=2e-5,
    )


def test_knob_flip_lifecycle_packed_data_keeps_working(monkeypatch):
    """Satellite contract: pack under x=int8, then flip knobs
    mid-process — the packed data must keep evaluating correctly
    through every path (warm starts / resumes / fleet stacking hand
    already-prepared data to later code that may see different env)."""
    data, _ = synth_lmm_data(KEY, 400, 4, 20)
    monkeypatch.setenv("STARK_FUSED_LMM", "1")
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "int8")
    m = FusedLMM(4, 20)
    fm = flatten_model(m)
    df = prepare_model_data(m, data)
    z = 0.2 * jax.random.normal(jax.random.PRNGKey(11), (fm.ndim,))
    v_int8, g_int8 = fm.potential_and_grad(z, df)
    # 1) x-dtype knob flips back to f32: the packed slab still routes
    #    through the fused op bit-identically (the data, not the env,
    #    carries the layout)
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "f32")
    v_flip, g_flip = fm.potential_and_grad(z, df)
    assert np.asarray(v_int8).tobytes() == np.asarray(v_flip).tobytes()
    assert np.asarray(g_int8).tobytes() == np.asarray(g_flip).tobytes()
    # 2) family knob flips off after the quantized prepare: the autodiff
    #    fallback dequantizes the same matrix (value matches at f32 tol)
    monkeypatch.setenv("STARK_FUSED_LMM", "0")
    v_fb, g_fb = fm.potential_and_grad(z, df)
    np.testing.assert_allclose(v_fb, v_int8, rtol=1e-5, atol=1e-4)
    scale = float(jnp.max(jnp.abs(g_int8))) + 1e-6
    np.testing.assert_allclose(
        np.asarray(g_fb) / scale, np.asarray(g_int8) / scale,
        rtol=1e-4, atol=2e-5,
    )
    # 3) re-prepare of already-packed data is a no-op (the resume path)
    monkeypatch.setenv("STARK_FUSED_LMM", "1")
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "int8")
    df2 = prepare_model_data(m, df)
    assert df2["xT"] is df["xT"] or (
        np.asarray(df2["xT"]).tobytes() == np.asarray(df["xT"]).tobytes()
    )


def test_irt_grid_packs_exactly(monkeypatch):
    """Binary response grids pack losslessly (no scale vector), and the
    knob-off fallback upcasts the packed grid transparently."""
    data, _ = synth_irt_data(KEY, 30, 10)
    monkeypatch.setenv("STARK_FUSED_IRT", "1")
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "int8")
    m = FusedIRT2PL(30, 10)
    fm = flatten_model(m)
    df = prepare_model_data(m, data)
    assert df["y_grid"].dtype == jnp.int8
    assert "y_grid_scale" not in df
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "f32")
    plain = IRT2PL(30, 10)
    dp = prepare_model_data(plain, data)
    fm_p = flatten_model(plain)
    z = 0.3 * jax.random.normal(jax.random.PRNGKey(2), (fm.ndim,))
    vp, gp = fm_p.potential_and_grad(z, dp)
    vf, gf = fm.potential_and_grad(z, df)  # fused on packed grid: exact data
    np.testing.assert_allclose(vp, vf, rtol=1e-5, atol=1e-4)
    # knob off after the packed-grid prepare: autodiff on the same slab
    monkeypatch.setenv("STARK_FUSED_IRT", "0")
    v_fb, _ = fm.potential_and_grad(z, df)
    np.testing.assert_allclose(v_fb, vp, rtol=1e-5, atol=1e-4)


def test_fleet_stacking_over_quant_prepared_data(monkeypatch):
    """FleetSpec stacks packed slabs + per-problem scale vectors along
    the problem axis, and the vmapped potential matches the per-problem
    sequential evaluations."""
    from stark_tpu.fleet import FleetSpec

    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "int8")
    monkeypatch.setenv("STARK_FUSED_GLM", "1")
    m = FusedPoissonRegression(4)
    dsets = [
        synth_poisson_data(jax.random.PRNGKey(i), 300, 4)[0]
        for i in range(3)
    ]
    spec = FleetSpec.from_problems(m, dsets)
    st = spec.prepared_stacked()
    assert st["xT"].dtype == jnp.int8 and st["xT"].shape[0] == 3
    assert st["xT_scale"].shape == (3, 4)
    fm = flatten_model(m)
    z = 0.1 * jax.random.normal(jax.random.PRNGKey(9), (fm.ndim,))
    per = [
        float(fm.potential(z, prepare_model_data(m, d))) for d in dsets
    ]
    vm = jax.vmap(lambda dd: fm.potential(z, dd))(st)
    np.testing.assert_allclose(np.asarray(vm), np.asarray(per), rtol=1e-6)


def test_scale_vector_row_axis_is_replicated(monkeypatch):
    """The data sharder must replicate xT_scale (a per-column global
    statistic), never row-shard it alongside the packed slab."""
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "int8")
    data, _ = synth_logistic_data(KEY, 200, 4)
    m = FusedLogistic(4)
    df = m.prepare_data(data)
    axes = m.data_row_axes(df)
    assert axes["xT"] == 1
    assert axes["xT_scale"] == -1  # replicated
    assert axes["y"] == 0


# --- bytes accounting + telemetry tags ------------------------------


def test_x_bytes_per_grad(monkeypatch):
    data, _ = synth_logistic_data(KEY, 100, 8)
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "int8")
    df = FusedLogistic(8).prepare_data(data)
    assert quantize.x_bytes_per_grad(df) == 100 * 8 * 1 + 8 * 4
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "f32")
    df32 = FusedLogistic(8).prepare_data(data)
    assert quantize.x_bytes_per_grad(df32) == 100 * 8 * 4
    assert quantize.x_bytes_per_grad({"y": jnp.ones((4,))}) is None


def test_x_stream_tags(monkeypatch):
    data, _ = synth_logistic_data(KEY, 100, 8)
    monkeypatch.delenv("STARK_FUSED_X_DTYPE", raising=False)
    # plain f32 / untagged models: NO fields (trace byte-identity)
    assert quantize.x_stream_tags("logistic", data) == {}
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "int8")
    assert quantize.x_stream_tags(None, data) == {}
    # raw data: bytes predicted from the row-matrix shape
    tags = quantize.x_stream_tags("logistic", data)
    assert tags["x_dtype"] == "int8"
    assert tags["x_bytes_per_grad"] == 100 * 8 * 1 + 8 * 4
    # prepared data: bytes measured from the packed slab itself
    df = FusedLogistic(8).prepare_data(data)
    assert quantize.x_stream_tags("logistic", df) == tags


def test_run_start_carries_x_stream_tags(monkeypatch):
    """An in-memory-traced sampling run under x=int8 stamps x_dtype +
    x_bytes_per_grad into run_start, and timeline_summary surfaces
    them; a knob-off run carries neither key."""
    from stark_tpu.profiling import timeline_summary

    data, _ = synth_poisson_data(KEY, 200, 4)
    events = []
    telemetry.add_event_listener(events.append)
    try:
        monkeypatch.setenv("STARK_FUSED_GLM", "1")
        monkeypatch.setenv("STARK_FUSED_X_DTYPE", "int8")
        stark_tpu.sample(
            FusedPoissonRegression(4), data, chains=2, kernel="hmc",
            num_warmup=10, num_samples=10, seed=0,
            trace=telemetry.RunTrace(path=None),
        )
        starts = [e for e in events if e.get("event") == "run_start"]
        assert starts and starts[0]["x_dtype"] == "int8"
        assert starts[0]["x_bytes_per_grad"] == 200 * 4 * 1 + 4 * 4
        ts = timeline_summary(events)
        assert ts["x_dtype"] == "int8"
        assert ts["x_bytes_per_grad"] == starts[0]["x_bytes_per_grad"]
        # knob-off: the keys are ABSENT (not null) — trace byte-identity
        events.clear()
        monkeypatch.setenv("STARK_FUSED_X_DTYPE", "f32")
        stark_tpu.sample(
            FusedPoissonRegression(4), data, chains=2, kernel="hmc",
            num_warmup=10, num_samples=10, seed=0,
            trace=telemetry.RunTrace(path=None),
        )
        s2 = [e for e in events if e.get("event") == "run_start"][0]
        assert "x_dtype" not in s2 and "x_bytes_per_grad" not in s2
        assert timeline_summary(events)["x_dtype"] is None
    finally:
        telemetry.remove_event_listener(events.append)

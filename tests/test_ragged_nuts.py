"""STARK_RAGGED_NUTS: step-synchronized NUTS block scheduling.

The contract (kernels/nuts_ragged.py): with the knob ON, every lane of a
vmapped NUTS block advances its own tree — one batched gradient
evaluation per lane per loop iteration — and the per-lane op/key
sequence is EXACTLY the legacy nested scan's, so draws / accept stats /
divergences / energies / grad counts / streaming-diag accumulators /
checkpoints are bit-identical on the single-runner and fleet paths, per
lane, independent of batch composition and across crash-resume replay.
With the knob OFF (default) nothing changes: no ragged code runs and the
metrics/trace trails carry none of the scheduling fields.

Plus the occupancy story: lane_iters accounting in the carry, the
useful-grad fraction strictly improving on a mixed-depth synthetic, and
the scheduler fields surfacing in traces / summarize_trace.

Cost discipline: ONE shared model/backend (the runner caches compiled
segments per (model, cfg) on the backend instance) and ONE shared
FleetSpec (fleet parts cache per (model, cfg)) across every end-to-end
run here, so the file pays each scheduler's XLA compile once.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stark_tpu import faults
from stark_tpu.backends.jax_backend import JaxBackend
from stark_tpu.checkpoint import load_checkpoint
from stark_tpu.fleet import FleetSpec, sample_fleet
from stark_tpu.kernels.base import init_state, stream_diag_init
from stark_tpu.kernels.nuts_ragged import ragged_nuts_enabled
from stark_tpu.model import flatten_model, prepare_model_data
from stark_tpu.models import EightSchools, eight_schools_data
from stark_tpu.models.eight_schools import SIGMA, Y
from stark_tpu.runner import sample_until_converged
from stark_tpu.sampler import SamplerConfig, make_block_runner
from stark_tpu.telemetry import RunTrace, read_trace, summarize_trace

#: fields that legitimately differ (timing) or ride only knob-on runs
_TIMING_KEYS = ("wall_s", "t_dispatch_s", "t_diag_s")
_SCHED_KEYS = ("ragged_nuts", "sched_iters", "lane_occupancy")

#: ONE model / data / backend for every single-runner test: the backend
#: caches compiled warmup segments + block runners per (model, cfg), so
#: knob-on/off/crash/resume runs share every legacy compile and pay the
#: ragged compile once
_MODEL = EightSchools()
_DATA = eight_schools_data()
_BACKEND = JaxBackend()


def _strip(history, extra=()):
    drop = set(_TIMING_KEYS) | set(_SCHED_KEYS) | set(extra)
    return [
        {k: v for k, v in rec.items() if k not in drop} for rec in history
    ]


def _block_fixture(chains=3, block=14, max_depth=6, seed=0,
                   steps=(0.25, 0.06, 0.45)):
    fm = flatten_model(_MODEL)
    pdata = prepare_model_data(_MODEL, _DATA)
    cfg = SamplerConfig(kernel="nuts", max_tree_depth=max_depth)
    pot = fm.bind(pdata)
    kz, kb = jax.random.split(jax.random.PRNGKey(seed))
    z0 = jax.vmap(fm.init_flat)(jax.random.split(kz, chains))
    state = jax.vmap(lambda z: init_state(pot, z))(z0)
    step = jnp.asarray(steps[:chains], jnp.float32)
    inv = jnp.ones((chains, fm.ndim), jnp.float32)
    bkeys = jax.random.split(kb, chains)
    return fm, pdata, cfg, state, step, inv, bkeys, block


def test_block_runner_bit_identity():
    """The core contract at the kernel boundary: every output of the
    ragged block runner equals the legacy scan's bitwise, and the carry's
    lane_iters equals the lane's useful grad evals (one leaf per live
    iteration by construction)."""
    fm, pdata, cfg, state, step, inv, bkeys, block = _block_fixture()
    legacy = jax.jit(jax.vmap(
        make_block_runner(fm, cfg, block), in_axes=(0, 0, 0, 0, None)))
    ragged = jax.jit(jax.vmap(
        make_block_runner(fm, cfg, block, ragged=True),
        in_axes=(0, 0, 0, 0, None)))
    out_l = jax.block_until_ready(legacy(bkeys, state, step, inv, pdata))
    out_r = jax.block_until_ready(ragged(bkeys, state, step, inv, pdata))
    # (state, zs, accept, divergent, energy, ngrad [, lane_iters])
    for a, b in zip(jax.tree.leaves(out_l[:6]), jax.tree.leaves(out_r[:6])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    lane_iters = np.asarray(out_r[6])
    np.testing.assert_array_equal(lane_iters, np.asarray(out_r[5]).sum(1))
    # the step-size spread really produced ragged lanes (else this file
    # tests nothing): the slow lane did >2x the fastest lane's work
    assert lane_iters.max() > 2 * lane_iters.min()


def test_block_runner_diag_bit_identity():
    """The streaming-diagnostics variant: the StreamDiagState carried
    through the ragged loop matches the legacy scan's leaf-for-leaf."""
    fm, pdata, cfg, state, step, inv, bkeys, block = _block_fixture()
    lags = 8
    diag0 = jax.vmap(lambda _: stream_diag_init(fm.ndim, lags))(
        jnp.arange(state.z.shape[0])
    )
    legacy = jax.jit(jax.vmap(
        make_block_runner(fm, cfg, block, diag_lags=lags),
        in_axes=(0, 0, 0, 0, 0, None)))
    ragged = jax.jit(jax.vmap(
        make_block_runner(fm, cfg, block, diag_lags=lags, ragged=True),
        in_axes=(0, 0, 0, 0, 0, None)))
    out_l = legacy(bkeys, state, diag0, step, inv, pdata)
    out_r = ragged(bkeys, state, diag0, step, inv, pdata)
    for a, b in zip(jax.tree.leaves(out_l), jax.tree.leaves(out_r[:7])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_lane_sequence_independent_of_batch():
    """Property test: a lane's per-step leapfrog/accept sequence (hence
    its draws) depends only on its own key/state/step — swapping its
    batch NEIGHBORS for lanes of very different tree depths changes
    nothing, bitwise.  (Same batch WIDTH on both sides: XLA respecializes
    per width with different fusion/rounding, which perturbs even the
    legacy kernel at the ulp level — composition independence, not
    width independence, is the scheduling contract.)"""
    fm, pdata, cfg, state, step, inv, bkeys, block = _block_fixture(
        chains=3, steps=(0.25, 0.06, 0.45))
    ragged = jax.jit(jax.vmap(
        make_block_runner(fm, cfg, block, ragged=True),
        in_axes=(0, 0, 0, 0, None)))

    def lane(tree, i):
        return jax.tree.map(lambda a: np.asarray(a)[i], tree)

    def take(idx):
        ix = jnp.asarray(idx)
        return jax.tree.map(lambda a: a[ix], (bkeys, state, step, inv))

    # lane 0 paired with the DEEP lane vs with the SHALLOW lane: its
    # own iteration count differs wildly relative to the batch's, but
    # its outputs must not move a bit
    with_deep = ragged(*take([0, 1]), pdata)
    with_shallow = ragged(*take([0, 2]), pdata)
    for a, b in zip(
        jax.tree.leaves(lane(with_deep[:6], 0)),
        jax.tree.leaves(lane(with_shallow[:6], 0)),
    ):
        np.testing.assert_array_equal(a, b)
    # and its per-lane iteration accounting is its own too
    assert np.asarray(with_deep[6])[0] == np.asarray(with_shallow[6])[0]


_RUN_KW = dict(
    chains=3, block_size=15, max_blocks=3, min_blocks=1, rhat_target=0.0,
    ess_target=1e9, num_warmup=30, kernel="nuts", max_tree_depth=6,
    seed=3, adaptive_blocks=False,
)


def _run_single(workdir, ragged, **kw):
    os.environ["STARK_RAGGED_NUTS"] = "1" if ragged else "0"
    try:
        trace_path = str(workdir / "t.jsonl")
        res = sample_until_converged(
            _MODEL, _DATA, backend=_BACKEND,
            checkpoint_path=str(workdir / "c.npz"),
            metrics_path=str(workdir / "m.jsonl"),
            trace=RunTrace(trace_path),
            **{**_RUN_KW, **kw},
        )
    finally:
        os.environ.pop("STARK_RAGGED_NUTS", None)
    return res, workdir, trace_path


@pytest.fixture(scope="module")
def single_runs(tmp_path_factory):
    """One knob-off and one knob-on adaptive-runner run (shared backend:
    the second pays only the ragged block compile) with full persistence
    + traces — shared by the identity, trace-purity, and resume tests."""
    td = tmp_path_factory.mktemp("ragged_runner")
    out = {}
    for tag, ragged in (("off", False), ("on", True)):
        d = td / tag
        d.mkdir()
        out[tag] = _run_single(d, ragged)
    return out


def test_runner_bit_identity_and_trace_fields(single_runs):
    """End-to-end through the adaptive runner: knob on vs off produce
    bit-identical draws, metrics history (modulo timing + the knob-on
    scheduling fields), and checkpoints; the knob-on trace carries the
    occupancy fields and summarize_trace's nutssched section; the
    knob-off trails carry NONE of them (byte-compat with pre-knob
    runs)."""
    res_off, d_off, tp_off = single_runs["off"]
    res_on, d_on, tp_on = single_runs["on"]
    np.testing.assert_array_equal(res_off.draws_flat, res_on.draws_flat)
    assert _strip(res_off.history) == _strip(res_on.history)
    a_off, _ = load_checkpoint(str(d_off / "c.npz"))
    a_on, _ = load_checkpoint(str(d_on / "c.npz"))
    assert sorted(a_off) == sorted(a_on)
    for k in a_off:
        np.testing.assert_array_equal(a_off[k], a_on[k])
    # metrics JSONL: knob-off lines carry no scheduling keys at all
    off_recs = [json.loads(l) for l in open(d_off / "m.jsonl")]
    on_recs = [json.loads(l) for l in open(d_on / "m.jsonl")]
    assert not any(k in r for r in off_recs for k in _SCHED_KEYS)
    on_blocks = [r for r in on_recs if r.get("event") == "block"]
    assert on_blocks and all(
        r.get("ragged_nuts") is True
        and 0.0 < r["lane_occupancy"] <= 1.0
        and r["sched_iters"] > 0
        for r in on_blocks
    )
    # trace events mirror the same split
    ev_off = read_trace(tp_off)
    ev_on = read_trace(tp_on)
    assert not any(k in e for e in ev_off for k in _SCHED_KEYS)
    s_on = summarize_trace(ev_on)
    assert s_on["nutssched"]["ragged"] is True
    assert 0.0 < s_on["nutssched"]["occupancy_min"] <= 1.0
    assert s_on["nutssched"]["blocks"] == len(on_blocks)
    assert summarize_trace(ev_off)["nutssched"] == {}


def test_runner_resume_replay(single_runs, tmp_path):
    """Crash-resume under the knob: a ragged run resumed from its
    block-1 checkpoint replays to the SAME draws as the uninterrupted
    legacy run (checkpoints carry no scheduler state — the knob can even
    flip across the restart)."""
    res_off, _d, _tp = single_runs["off"]
    ck = str(tmp_path / "c.npz")
    os.environ["STARK_RAGGED_NUTS"] = "1"
    faults.configure("runner.block.post=crash@1")
    try:
        with pytest.raises(faults.InjectedFault):
            sample_until_converged(
                _MODEL, _DATA, backend=_BACKEND, checkpoint_path=ck,
                **_RUN_KW,
            )
        faults.configure(None)
        resumed = sample_until_converged(
            _MODEL, _DATA, backend=_BACKEND, checkpoint_path=ck,
            resume_from=ck, **_RUN_KW,
        )
    finally:
        faults.configure(None)
        os.environ.pop("STARK_RAGGED_NUTS", None)
    np.testing.assert_array_equal(res_off.draws_flat, resumed.draws_flat)


#: ONE fleet spec for every fleet test: `fleet._PARTS_CACHE` keys on the
#: (model, cfg) pair, so the runs below share the compiled fleet parts
def _make_fleet_spec(n=3, seed=0):
    rng = np.random.default_rng(seed)
    y, sig = np.asarray(Y), np.asarray(SIGMA)
    return FleetSpec.from_problems(
        _MODEL,
        [{"y": (y + rng.normal(0, 2.0, y.shape)).astype(np.float32),
          "sigma": sig} for _ in range(n)],
    )


_FLEET_SPEC = _make_fleet_spec()

_FLEET_KW = dict(
    chains=2, block_size=15, max_blocks=3, min_blocks=1, num_warmup=30,
    ess_target=1e9, rhat_target=0.0, seed=0, kernel="nuts",
    max_tree_depth=6,
)


def _run_fleet(ragged, **kw):
    os.environ["STARK_RAGGED_NUTS"] = "1" if ragged else "0"
    try:
        return sample_fleet(_FLEET_SPEC, **{**_FLEET_KW, **kw})
    finally:
        os.environ.pop("STARK_RAGGED_NUTS", None)


@pytest.fixture(scope="module")
def fleet_runs(tmp_path_factory):
    """One legacy and one ragged fleet run over the shared spec, with
    metrics — shared by the fleet identity and crash-resume tests."""
    td = tmp_path_factory.mktemp("ragged_fleet")
    out = {}
    for tag, ragged in (("off", False), ("on", True)):
        d = td / tag
        d.mkdir()
        out[tag] = (
            _run_fleet(ragged, metrics_path=str(d / "m.jsonl")), d
        )
    return out


def test_fleet_bit_identity(fleet_runs):
    """The fleet path (doubly-vmapped lanes): ragged vs legacy per-problem
    draws are bit-identical, and the knob-on fleet metrics carry the
    lane-occupancy fields while knob-off ones don't."""
    res_off, d_off = fleet_runs["off"]
    res_on, d_on = fleet_runs["on"]
    for a, b in zip(res_off.problems, res_on.problems):
        np.testing.assert_array_equal(a.draws_flat, b.draws_flat)
    off_recs = [json.loads(l) for l in open(d_off / "m.jsonl")]
    on_recs = [json.loads(l) for l in open(d_on / "m.jsonl")]
    assert not any(k in r for r in off_recs for k in _SCHED_KEYS)
    fb = [r for r in on_recs if r.get("event") == "fleet_block"]
    assert fb and all(
        r.get("ragged_nuts") is True and 0.0 < r["lane_occupancy"] <= 1.0
        for r in fb
    )


def test_fleet_crash_resume_replay(fleet_runs, tmp_path):
    """Fleet crash-resume under the knob: the resumed ragged fleet
    replays to draws bit-identical to the uninjected legacy fleet."""
    baseline, _d = fleet_runs["off"]
    ck = str(tmp_path / "fleet.ckpt.npz")
    faults.configure("fleet.block.post=crash@1")
    try:
        with pytest.raises(faults.InjectedFault):
            _run_fleet(True, checkpoint_path=ck)
    finally:
        faults.configure(None)
    resumed = _run_fleet(True, checkpoint_path=ck, resume_from=ck)
    for a, b in zip(baseline.problems, resumed.problems):
        np.testing.assert_array_equal(a.draws_flat, b.draws_flat)


def test_occupancy_monotone_on_mixed_depths():
    """Occupancy monotonicity: on lanes of deliberately different tree
    depths the ragged schedule never executes MORE batched gradient
    evaluations than the legacy nested loops, and its useful-grad
    fraction is at least the legacy one (strictly better when the lanes
    actually de-synchronize — which the fixture's equal-step
    per-transition depth variance guarantees)."""
    from stark_tpu.benchmarks import _GradEvalProbe

    # near-exchangeable lanes: per-transition depth variance makes the
    # argmax lane CHANGE across rounds, which is exactly when the legacy
    # max-lane sync wastes evaluations (a single always-deepest lane is
    # the one case where legacy is already tight — the octave-spread
    # fixture above lands there, so this test uses equal steps)
    chains = 6
    fm, pdata, cfg, state, step, inv, bkeys, block = _block_fixture(
        chains=chains, block=24, steps=(0.15,) * chains)
    probe = _GradEvalProbe(fm)
    probe.calls = 0
    jax.block_until_ready(
        jax.jit(jax.vmap(probe.bind(pdata).value_and_grad))(state.z)
    )
    per_eval = max(probe.snapshot(), 1)
    executed = {}
    useful = None
    for name, ragged in (("legacy", False), ("ragged", True)):
        fn = jax.jit(jax.vmap(
            make_block_runner(probe, cfg, block, ragged=ragged),
            in_axes=(0, 0, 0, 0, None)))
        probe.calls = 0
        out = jax.block_until_ready(fn(bkeys, state, step, inv, pdata))
        executed[name] = probe.snapshot() // per_eval
        u = int(np.asarray(out[5]).sum())
        assert useful is None or useful == u  # identical useful work
        useful = u
        if ragged:
            # carry accounting == dispatch-probe truth
            assert executed[name] == int(np.asarray(out[6]).max())
    assert executed["ragged"] <= executed["legacy"]
    occ = {k: useful / (v * chains) for k, v in executed.items()}
    assert occ["ragged"] > occ["legacy"]


def test_knob_and_config_gating(monkeypatch):
    """ragged_nuts_enabled: default off; on only for NUTS configs with
    no in-scan heartbeat.  make_block_runner(ragged=True) refuses
    non-NUTS kernels loudly."""
    monkeypatch.delenv("STARK_RAGGED_NUTS", raising=False)
    assert not ragged_nuts_enabled()
    monkeypatch.setenv("STARK_RAGGED_NUTS", "1")
    assert ragged_nuts_enabled()
    assert ragged_nuts_enabled(SamplerConfig(kernel="nuts"))
    assert not ragged_nuts_enabled(SamplerConfig(kernel="hmc"))
    assert not ragged_nuts_enabled(
        SamplerConfig(kernel="nuts", progress_every=10)
    )
    fm = flatten_model(_MODEL)
    with pytest.raises(ValueError, match="NUTS"):
        make_block_runner(
            fm, SamplerConfig(kernel="hmc"), 10, ragged=True
        )

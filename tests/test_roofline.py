"""Roofline sanity-gate tests (VERDICT r2 #3 / ADVICE r2 medium).

The axon tunnel memoizes repeated (executable, args) executions, which can
fake >spec-peak HBM rates; tools/roofline.py must never commit such rows as
real data.  These tests pin the gate's behavior and assert the committed
artifact itself contains no un-flagged impossible rates.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
from roofline import V5E_PEAK_GBS, gate  # noqa: E402

_RESULTS = os.path.join(
    os.path.dirname(__file__), "..", "tools", "roofline_results.json"
)


def test_gate_passes_sane_rates():
    entry = {"per_dispatch_gbs": 50.0, "amortized_gbs": 320.0,
             "pct_of_spec_peak": 39.0}
    assert gate(entry)
    assert "invalid_memoized" not in entry
    assert entry["pct_of_spec_peak"] == 39.0


@pytest.mark.parametrize("field", ["per_dispatch_gbs", "amortized_gbs"])
def test_gate_flags_impossible_rates(field):
    entry = {"per_dispatch_gbs": 100.0, "amortized_gbs": 300.0,
             "pct_of_spec_peak": 36.0}
    entry[field] = V5E_PEAK_GBS * 10  # the measured memoization signature
    assert not gate(entry)
    assert entry["invalid_memoized"] is True
    assert entry["pct_of_spec_peak"] is None


def test_committed_artifact_has_no_unflagged_impossible_rows():
    with open(_RESULTS) as f:
        results = json.load(f)
    rows = list(results["cases"]) + [results["stream"]]
    for row in rows:
        if row.get("invalid_memoized"):
            continue
        assert row["per_dispatch_gbs"] <= V5E_PEAK_GBS, row
        assert row["amortized_gbs"] <= V5E_PEAK_GBS, row


def test_committed_artifact_grouped_rows_gated():
    """Grouped-kernel rows (r5) obey the same memoization gate contract
    when present in the committed artifact."""
    with open(_RESULTS) as f:
        results = json.load(f)
    for row in results.get("grouped", []) + results.get("grouped_lmm", []):
        if row.get("invalid_memoized"):
            continue
        assert row["amortized_gbs"] <= V5E_PEAK_GBS, row
        assert "grouped" in row["case"], row

"""Adaptive runner tests: run-until-R-hat, metrics JSONL, checkpoint/resume."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

import stark_tpu
from stark_tpu.checkpoint import load_checkpoint, save_checkpoint
from stark_tpu.model import Model, ParamSpec


class StdNormal2(Model):
    def param_spec(self):
        return {"x": ParamSpec((2,))}

    def log_prior(self, p):
        return -0.5 * jnp.sum(p["x"] ** 2)

    def log_lik(self, p, data):
        return jnp.zeros(())


def test_sample_until_converged(tmp_path):
    metrics = str(tmp_path / "metrics.jsonl")
    ckpt = str(tmp_path / "state.npz")
    post = stark_tpu.sample_until_converged(
        StdNormal2(),
        chains=4,
        block_size=100,
        max_blocks=20,
        rhat_target=1.02,
        ess_target=200.0,
        num_warmup=150,
        kernel="nuts",
        max_tree_depth=6,
        seed=0,
        metrics_path=metrics,
        checkpoint_path=ckpt,
    )
    assert post.converged, post.history
    assert post.max_rhat() < 1.02
    assert post.min_ess() > 200.0
    # metrics JSONL: warmup event + one line per block
    lines = [json.loads(l) for l in open(metrics)]
    assert lines[0]["event"] == "warmup_done"
    assert sum(1 for l in lines if l["event"] == "block") == len(post.history)
    # checkpoint written and loadable
    arrays, meta = load_checkpoint(ckpt)
    assert arrays["z"].shape == (4, 2)
    assert meta["blocks_done"] == len(post.history)


def test_resume_from_checkpoint(tmp_path):
    ckpt = str(tmp_path / "state.npz")
    post1 = stark_tpu.sample_until_converged(
        StdNormal2(), chains=2, block_size=50, max_blocks=2, min_blocks=2,
        rhat_target=0.5,  # unreachable -> runs exactly max_blocks
        num_warmup=100, kernel="hmc", num_leapfrog=8, seed=1,
        checkpoint_path=ckpt,
    )
    assert not post1.converged
    assert post1.num_samples == 100
    post2 = stark_tpu.sample_until_converged(
        StdNormal2(), block_size=50, max_blocks=4, min_blocks=2,
        rhat_target=0.5, num_warmup=100, kernel="hmc", num_leapfrog=8,
        resume_from=ckpt,
    )
    # resumed run continues from 2 blocks of saved draws to 4 blocks total
    assert post2.num_samples == 200
    assert post2.num_chains == 2


def test_checkpoint_atomic_roundtrip(tmp_path):
    path = str(tmp_path / "c.npz")
    arrays = {"a": np.arange(6).reshape(2, 3), "b": np.ones(4, np.float32)}
    save_checkpoint(path, arrays, {"k": 1})
    out, meta = load_checkpoint(path)
    np.testing.assert_array_equal(out["a"], arrays["a"])
    np.testing.assert_array_equal(out["b"], arrays["b"])
    assert meta == {"k": 1}

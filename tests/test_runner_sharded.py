"""Adaptive runner + supervision composed with ShardedBackend (VERDICT r2
missing #4): the convergence-driven block protocol, checkpoint/resume, and
failure supervision must work WITH chains/data sharded over the mesh — not
only on a single device.
"""

import json
import os

import jax
import numpy as np
import pytest

import stark_tpu
from stark_tpu import supervise
from stark_tpu.backends.sharded import ShardedBackend
from stark_tpu.models.logistic import Logistic, synth_logistic_data
from stark_tpu.parallel.mesh import make_mesh
from stark_tpu.supervise import supervised_sample


@pytest.fixture(scope="module")
def setup():
    model = Logistic(num_features=4)
    data, _ = synth_logistic_data(jax.random.PRNGKey(0), 1024, 4)
    return model, data


CHEES_KW = dict(
    kernel="chees",
    chains=8,
    num_warmup=150,
    block_size=50,
    max_blocks=12,
    min_blocks=2,
    rhat_target=1.02,
    ess_target=200.0,
    init_step_size=0.1,
)


def _mesh():
    return make_mesh({"data": 2, "chains": 4})


@pytest.mark.slow
def test_adaptive_chees_on_mesh_matches_single_device(setup):
    """Same seed, same schedule: the mesh run's collective adaptation must
    reproduce the single-device ensemble statistics (psum of shard sums ==
    global sum), so the posterior summaries agree."""
    model, data = setup
    post_mesh = stark_tpu.sample_until_converged(
        model, data, backend=ShardedBackend(_mesh()), seed=3, **CHEES_KW
    )
    post_one = stark_tpu.sample_until_converged(
        model, data, seed=3, **CHEES_KW
    )
    assert post_mesh.converged and post_one.converged
    for name in post_mesh.draws:
        np.testing.assert_allclose(
            post_mesh.draws[name].mean(axis=(0, 1)),
            post_one.draws[name].mean(axis=(0, 1)),
            atol=0.15,
        )


@pytest.mark.slow
def test_adaptive_nuts_on_mesh_converges(setup):
    """Per-chain kernels through the mesh adaptive path (shard_mapped
    segmented warmup + block runner)."""
    model, data = setup
    post = stark_tpu.sample_until_converged(
        model, data, backend=ShardedBackend(_mesh()), seed=0,
        kernel="nuts", max_tree_depth=6, chains=8, num_warmup=200,
        block_size=50, max_blocks=10, min_blocks=2,
        rhat_target=1.02, ess_target=200.0,
    )
    assert post.converged
    assert post.draws_flat.shape[0] == 8


@pytest.mark.slow
def test_sharded_backend_dispatch_bounded_nuts(setup):
    """ShardedBackend.run with dispatch_steps: bounded device programs for
    the per-chain kernels (previously chees-only)."""
    model, data = setup
    post = stark_tpu.sample(
        model, data, backend=ShardedBackend(_mesh(), dispatch_steps=60),
        chains=8, num_warmup=200, num_samples=200, seed=1,
    )
    assert post.max_rhat() < 1.05
    assert post.num_samples == 200


@pytest.mark.slow
def test_supervised_sharded_chees_kill_resume(tmp_path, monkeypatch, setup):
    """THE composition the flagship bench relies on: supervised ChEES over
    the mesh, killed mid-sampling, resumes from the block checkpoint on
    the mesh (state re-placed from host numpy) and finishes."""
    model, data = setup
    wd = str(tmp_path / "run")
    backend = ShardedBackend(_mesh())
    real = stark_tpu.runner.sample_until_converged
    calls = {"n": 0, "resumes": []}

    def flaky(m, d=None, **kw):
        calls["n"] += 1
        calls["resumes"].append(kw.get("resume_from"))
        if calls["n"] == 1:
            # two real blocks land a checkpoint, then the "device" dies
            real(m, d, **dict(kw, max_blocks=2, rhat_target=0.5))
            raise RuntimeError("injected mesh fault")
        return real(m, d, **kw)

    monkeypatch.setattr(supervise, "sample_until_converged", flaky,
                        raising=False)
    monkeypatch.setattr(stark_tpu.runner, "sample_until_converged", flaky)
    post = supervised_sample(
        model, data, workdir=wd, backend=backend, seed=0, max_restarts=2,
        **CHEES_KW,
    )
    assert post.converged
    assert calls["n"] == 2
    assert calls["resumes"][0] is None
    assert calls["resumes"][1] is not None  # resumed from the checkpoint
    lines = [json.loads(l) for l in open(os.path.join(wd, "metrics.jsonl"))]
    assert sum(1 for l in lines if l["event"] == "restart") == 1
    # the resumed run keeps the pre-kill draws: its first block record
    # continues from the checkpointed count, not from zero
    resumed_blocks = [l for l in lines if l["event"] == "block"]
    assert resumed_blocks[-1]["draws_per_chain"] >= 150

"""Posterior read plane (stark_tpu/serving.py) contracts.

Sidecar summaries (write-once at convergence, atomic, computed
fallback), the multi-tenant LRU (hit/miss accounting, capacity
eviction, `STARK_SERVE_CACHE=0` off-switch), the batched predictive
evaluator (parity with the per-draw reference at both links, a
quantized tenant served off the packed slab via the scale-fold
identity, `STARK_SERVE_PREDICT_DRAWS` tail cap), telemetry knob-off
silence (`STARK_SERVE_TELEMETRY=0`), the statusd ``/posterior/<id>/*``
endpoint contracts (incl. `STARK_SERVE_ROOT` auto-attach and the
schema-3 ``/status`` `serving` sub-object), DonorPool position
ensembles + checkpoint ride, and `donor_pool_from_store` — the
incremental-reconvergence seed.  `STARK_SERVE_SKETCH` caps the sidecar
quantile subsample.

Read-only discipline: nothing here mutates a store after sampling —
the read plane must never write under its root.
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from stark_tpu import serving, telemetry
from stark_tpu.drawstore import DrawStore
from stark_tpu.serving import PosteriorStore, PredictRequest
from stark_tpu.statusd import StatusServer


def _mk_store(root, pid, *, chains=2, draws=40, dim=3, seed=0,
              sidecar=True):
    """One tenant's .stkr store (+ optional sidecar) under root."""
    path = os.path.join(str(root), f"p_{pid}.stkr")
    rng = np.random.default_rng(seed)
    with DrawStore(path, chains=chains, dim=dim) as ds:
        ds.append(rng.standard_normal((chains, draws, dim))
                  .astype(np.float32))
    if sidecar:
        serving.write_summary(
            path, problem_id=pid, model_tag="T", status="converged",
            min_ess=123.0, max_rhat=1.01,
            adaptation={"step_size": 0.3,
                        "inv_mass_diag": np.ones(dim)},
        )
    return path


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _post(port, path, obj):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# -------------------------------------------------------------------------
# summary sidecar
# -------------------------------------------------------------------------


def test_sidecar_roundtrip_and_schema(tmp_path):
    path = _mk_store(tmp_path, "t0", chains=2, draws=50, dim=3)
    s = serving.read_summary(path)
    assert s is not None and s["schema"] == serving.SUMMARY_SCHEMA
    assert s["problem_id"] == "t0" and s["status"] == "converged"
    assert (s["n_draws"], s["chains"], s["dim"]) == (50, 2, 3)
    assert s["min_ess"] == 123.0 and s["max_rhat"] == 1.01
    assert s["adaptation"]["step_size"] == 0.3
    assert len(s["adaptation"]["inv_mass_diag"]) == 3
    # moments match a float64 pass over the real draws
    from stark_tpu.drawstore import read_draws

    draws, _, _ = read_draws(path)
    flat = draws.reshape(-1, 3)
    np.testing.assert_allclose(
        s["mean"], flat.mean(axis=0, dtype=np.float64), atol=1e-6
    )
    np.testing.assert_allclose(
        s["std"], flat.std(axis=0, dtype=np.float64), atol=1e-6
    )
    assert len(s["quantiles"]) == len(serving.QUANTILE_PROBS)
    assert s["quantile_probs"] == list(serving.QUANTILE_PROBS)


def test_summary_computed_fallback_without_sidecar(tmp_path):
    _mk_store(tmp_path, "bare", sidecar=False)
    store = PosteriorStore(str(tmp_path))
    s = store.summary("bare")
    assert s["problem_id"] == "bare" and s["status"] is None
    assert s["n_draws"] == 40
    # the fallback never persists: the root stays read-only
    assert sorted(os.listdir(tmp_path)) == ["p_bare.stkr"]


def test_sketch_cap_knob_bounds_the_subsample(tmp_path, monkeypatch):
    """STARK_SERVE_SKETCH caps the quantile sketch rows: a cap at or
    above the store is exact; a tiny cap coarsens quantiles only —
    mean/std stay full-store float64 either way (floor 64)."""
    path = _mk_store(tmp_path, "q", chains=2, draws=100, dim=2,
                     sidecar=False)
    from stark_tpu.drawstore import read_draws

    draws, _, _ = read_draws(path)
    flat = draws.reshape(-1, 2)
    monkeypatch.setenv("STARK_SERVE_SKETCH", "100000")
    exact = serving.compute_summary(draws)
    np.testing.assert_allclose(
        exact["quantiles"],
        np.quantile(np.asarray(flat, np.float64),
                    serving.QUANTILE_PROBS, axis=0),
        atol=1e-7,
    )
    monkeypatch.setenv("STARK_SERVE_SKETCH", "64")
    coarse = serving.compute_summary(draws)
    np.testing.assert_allclose(coarse["mean"], exact["mean"], atol=1e-7)
    np.testing.assert_allclose(coarse["std"], exact["std"], atol=1e-7)
    q = np.asarray(coarse["quantiles"])
    assert q.shape == (len(serving.QUANTILE_PROBS), 2)
    assert np.all(np.isfinite(q)) and np.all(np.diff(q, axis=0) >= 0)


# -------------------------------------------------------------------------
# LRU
# -------------------------------------------------------------------------


def test_lru_hit_miss_eviction_and_cache_off(tmp_path, monkeypatch):
    for pid in ("a", "b"):
        _mk_store(tmp_path, pid, seed=ord(pid))
    store = PosteriorStore(str(tmp_path), capacity=8)
    assert store.ids() == ["a", "b"]
    store.summary("a")            # cold open
    store.summary("a")            # resident
    store.draws("a")              # still resident (shared tenant entry)
    st = store.cache_stats()
    assert (st["misses"], st["hits"]) == (1, 2)
    store.evict("a")              # the bench's cold knob
    store.summary("a")
    assert store.cache_stats()["misses"] == 2
    # capacity-1 store: the second tenant evicts the first
    small = PosteriorStore(str(tmp_path), capacity=1)
    small.summary("a"); small.summary("b"); small.summary("a")
    assert small.cache_stats() == {
        "entries": 1, "capacity": 1, "hits": 0, "misses": 3,
        "requests": 3,
    }
    # STARK_SERVE_CACHE=0 disables caching entirely (env-driven default)
    monkeypatch.setenv("STARK_SERVE_CACHE", "0")
    off = PosteriorStore(str(tmp_path))
    assert off.capacity == 0
    off.summary("a"); off.summary("a")
    st = off.cache_stats()
    assert st["entries"] == 0 and st["misses"] == 2 and st["hits"] == 0
    assert PosteriorStore(str(tmp_path), capacity=3).capacity == 3
    with pytest.raises(KeyError):
        store.summary("nope")


# -------------------------------------------------------------------------
# batched predictive evaluator
# -------------------------------------------------------------------------


def test_predict_parity_batched_quantized_and_draw_cap(tmp_path,
                                                       monkeypatch):
    """The one-dispatch batched evaluator matches the per-draw reference
    loop at <=1e-5 for every tenant in a mixed batch — including a
    tenant served off its packed int8 design (scale folds into beta,
    the bytes are never dequantized) — and STARK_SERVE_PREDICT_DRAWS
    caps the draw tail entering the evaluator."""
    chains, dim, m = 2, 3, 5
    for i, pid in enumerate(("p0", "p1", "p2")):
        _mk_store(tmp_path, pid, chains=chains, draws=30, dim=dim,
                  seed=10 + i)
    store = PosteriorStore(str(tmp_path))
    rng = np.random.default_rng(99)
    xq_design = rng.standard_normal((m, dim)).astype(np.float32)
    store.register_design("p0", xq_design, dtype="int8")
    reqs = [
        PredictRequest("p0", None),                       # packed design
        PredictRequest(
            "p1", rng.standard_normal((m, dim)).astype(np.float32)
        ),
        PredictRequest(
            "p2", rng.standard_normal((m, dim)).astype(np.float32),
            link="logistic",
        ),
    ]
    out = store.predict(reqs)
    assert [o["problem_id"] for o in out] == ["p0", "p1", "p2"]
    for req, o in zip(reqs, out):
        beta, xq, scale, _cache = store._predict_operands(req)
        x_eff = np.asarray(xq, np.float32) * scale[None, :]
        ref_mean, ref_q = serving.predict_reference(
            beta, x_eff, link=req.link
        )
        np.testing.assert_allclose(o["mean"], ref_mean, atol=1e-5)
        np.testing.assert_allclose(o["quantiles"], ref_q, atol=1e-5)
        assert o["quantile_probs"] == list(serving.QUANTILE_PROBS)
    # the quantized tenant really serves off int8 bytes
    xq0, scale0 = store._designs["p0"]
    assert np.asarray(xq0).dtype == np.int8
    assert not np.allclose(scale0, 1.0)
    # draw-tail cap: ceil(cap/chains) tail rows -> cap draws
    monkeypatch.setenv("STARK_SERVE_PREDICT_DRAWS", "16")
    capped = store.predict([reqs[1]])[0]
    assert capped["draws_used"] == 16
    monkeypatch.delenv("STARK_SERVE_PREDICT_DRAWS")
    assert store.predict([reqs[1]])[0]["draws_used"] == 30 * chains
    # malformed query: no x and no registered design
    with pytest.raises(KeyError):
        store.predict([PredictRequest("p1", None)])
    # dim-mismatched x is a ValueError, not a crash
    with pytest.raises(ValueError):
        store.predict([PredictRequest(
            "p1", np.zeros((m, dim + 1), np.float32)
        )])


# -------------------------------------------------------------------------
# telemetry knob
# -------------------------------------------------------------------------


def test_serve_telemetry_knob_off_silences_events(tmp_path, monkeypatch):
    """Every read emits a `serve_request` event by default;
    STARK_SERVE_TELEMETRY=0 silences the family — responses and cache
    accounting identical (the read plane is host-side either way)."""
    _mk_store(tmp_path, "t")
    seen = []
    telemetry.add_event_listener(seen.append)
    try:
        store = PosteriorStore(str(tmp_path))
        store.summary("t")
        store.draws("t")
        store.predict([PredictRequest(
            "t", np.zeros((2, 3), np.float32)
        )])
        events = [r for r in seen if r.get("event") == "serve_request"]
        assert [e["endpoint"] for e in events] == \
            ["summary", "draws", "predict"]
        assert events[0]["cache"] == "miss" and events[1]["cache"] == "hit"
        assert all(e["ok"] for e in events)
        assert events[2]["batch"] == 1
        # knob off: same reads, zero new events, same answers
        monkeypatch.setenv("STARK_SERVE_TELEMETRY", "0")
        before = len(seen)
        quiet = PosteriorStore(str(tmp_path))
        s_on, s_off = store.summary("t"), quiet.summary("t")
        assert s_on == s_off
        assert quiet.cache_stats()["requests"] == 1
        assert len(seen) == before
    finally:
        telemetry.remove_event_listener(seen.append)


# -------------------------------------------------------------------------
# statusd endpoints
# -------------------------------------------------------------------------


def test_statusd_posterior_endpoint_contracts(tmp_path):
    """The read-plane routes over a live daemon: 503 detached, then
    summary / draws / predict against an attached store, 404 unknown
    tenant, 400 malformed predict, and the schema-3 /status `serving`
    sub-object fed by the request stream."""
    _mk_store(tmp_path, "t8", chains=2, draws=25, dim=3)
    srv = StatusServer(0, host="127.0.0.1").start()
    try:
        code, body = _get(srv.port, "/posterior/t8/summary")
        assert code == 503 and "STARK_SERVE_ROOT" in json.loads(body)["error"]
        srv.attach_serving(PosteriorStore(str(tmp_path)))
        # /posterior/<id>/summary
        code, body = _get(srv.port, "/posterior/t8/summary")
        assert code == 200
        s = json.loads(body)
        assert s["problem_id"] == "t8" and s["status"] == "converged"
        # /posterior/<id>/draws (?n= tail)
        code, body = _get(srv.port, "/posterior/t8/draws?n=5")
        assert code == 200
        d = json.loads(body)
        assert (d["n_draws"], d["chains"], d["dim"]) == (25, 2, 3)
        assert d["returned"] == 5 and len(d["draws"]) == 5
        # /posterior/<id>/predict (POST; explicit x)
        code, body = _post(
            srv.port, "/posterior/t8/predict",
            {"x": [[0.0, 0.0, 0.0], [1.0, 1.0, 1.0]],
             "link": "identity"},
        )
        assert code == 200
        p = json.loads(body)
        assert len(p["mean"]) == 2 and p["draws_used"] == 50
        assert len(p["quantiles"]) == len(serving.QUANTILE_PROBS)
        # error contracts
        assert _get(srv.port, "/posterior/ghost/summary")[0] == 404
        assert _get(srv.port, "/posterior/t8/frobnicate")[0] == 404
        code, _ = _post(srv.port, "/posterior/t8/predict",
                        {"x": [[1.0]]})        # k mismatch
        assert code == 400
        assert _post(srv.port, "/posterior/ghost/predict", {})[0] == 404
        # /status grows the `serving` rollup (contract schema 4 carries
        # the lineage jobs rollup too)
        code, body = _get(srv.port, "/status")
        assert code == 200
        snap = json.loads(body)
        assert snap["schema"] == 4
        sv = snap["serving"]
        assert sv["requests"] >= 4 and sv["misses"] >= 1
        assert set(sv["by_endpoint"]) >= {"summary", "draws", "predict"}
        assert sv["qps"] > 0
        # metrics family materialized from the same stream
        code, text = _get(srv.port, "/metrics")
        assert code == 200
        assert "stark_serve_requests_total" in text
        assert "stark_serve_cache_misses_total" in text
        assert "stark_serve_request_seconds_bucket" in text
    finally:
        srv.stop()


def test_serve_root_env_auto_attach(tmp_path, monkeypatch):
    """STARK_SERVE_ROOT=<fleet store root> attaches the read plane at
    daemon start (maybe_start_from_env); a bad root degrades to 503s,
    never a failed start."""
    from stark_tpu import statusd

    _mk_store(tmp_path, "auto")
    monkeypatch.setenv("STARK_SERVE_ROOT", str(tmp_path))
    srv = statusd.maybe_start_from_env(0)
    try:
        assert srv is not None and srv.serving is not None
        code, body = _get(srv.port, "/posterior/auto/summary")
        assert code == 200 and json.loads(body)["problem_id"] == "auto"
    finally:
        statusd.stop_status_server()
    # unset -> detached daemon, /posterior/* answers 503
    monkeypatch.delenv("STARK_SERVE_ROOT")
    srv = statusd.maybe_start_from_env(0)
    try:
        assert srv is not None and srv.serving is None
        assert _get(srv.port, "/posterior/auto/summary")[0] == 503
    finally:
        statusd.stop_status_server()


# -------------------------------------------------------------------------
# incremental reconvergence: position ensembles + the store-seeded pool
# -------------------------------------------------------------------------


def test_donor_pool_position_ensembles_and_checkpoint_ride():
    """DonorPool's ensemble side mirrors the moment contract: finite-
    validated on write AND read, latest-finite-wins, and it rides
    state_dict/load_state (the fleet checkpoint representation)."""
    from stark_tpu.fleet import DonorPool

    pool = DonorPool()
    assert pool.ensemble("m") is None
    bad = np.ones((2, 3), np.float32); bad[1, 1] = np.nan
    assert not pool.add_ensemble("m", bad)
    assert not pool.add_ensemble("m", np.ones(3, np.float32))  # 1-D
    assert pool.ensemble("m") is None
    first = np.full((2, 3), 1.5, np.float32)
    second = np.full((2, 3), 2.5, np.float32)
    assert pool.add_ensemble("m", first)
    assert pool.add_ensemble("m", second)           # latest finite wins
    np.testing.assert_array_equal(pool.ensemble("m"), second)
    assert not pool.add_ensemble("m", bad)          # rejected, kept
    np.testing.assert_array_equal(pool.ensemble("m"), second)
    # moments and ensemble ride the same checkpoint dict
    assert pool.add("m", np.array([0.1, 0.2]), np.ones((2, 3)))
    pool2 = DonorPool()
    pool2.load_state(pool.state_dict())
    np.testing.assert_array_equal(pool2.ensemble("m"), second)
    step, _im, n = pool2.summary("m")
    assert n == 1 and np.isfinite(step)
    # a hand-NaN'd checkpoint cannot smuggle an ensemble past load
    state = pool.state_dict()
    state["m"]["ensemble"][0][0] = float("nan")
    pool3 = DonorPool()
    pool3.load_state(state)
    assert pool3.ensemble("m") is None
    assert pool3.summary("m") is not None           # moments unaffected


def test_donor_pool_from_store_seeds_both_donors(tmp_path):
    """`donor_pool_from_store` = sidecar adaptation -> moment donor,
    last draw row -> position donor; a store without a sidecar still
    yields the position ensemble."""
    path = _mk_store(tmp_path, "y", chains=2, draws=30, dim=3)
    pool = serving.donor_pool_from_store(path, "EightSchools")
    step, im, n = pool.summary("EightSchools")
    assert n == 1 and abs(step - 0.3) < 1e-9
    np.testing.assert_allclose(im, np.ones(3))
    ens = pool.ensemble("EightSchools")
    from stark_tpu.drawstore import read_draws

    draws, _, _ = read_draws(path)
    np.testing.assert_array_equal(ens, draws[-1].astype(np.float32))
    # sidecar-less store: moments absent, positions still donated
    bare = _mk_store(tmp_path, "z", sidecar=False)
    pool2 = serving.donor_pool_from_store(bare, "M")
    assert pool2.summary("M") is None
    assert pool2.ensemble("M") is not None

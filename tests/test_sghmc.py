"""SG-HMC kernel + runner tests (benchmark config 5 capability).

Correctness oracle: conjugate normal-mean posterior (known mean/variance);
SG-HMC is asymptotically biased at finite step size so tolerances are loose
but tight enough to catch sign/scale errors in the friction update.
"""

import jax
import jax.numpy as jnp
import numpy as np

import stark_tpu
from stark_tpu.kernels.sghmc import make_minibatch_grad, sghmc_init, sghmc_step
from stark_tpu.model import Model, ParamSpec, flatten_model
from stark_tpu.sghmc import sghmc_sample


import pytest

class NormalMean(Model):
    """y_i ~ N(mu, 1), mu ~ N(0, prior_sd): conjugate, posterior known."""

    def __init__(self, prior_sd=10.0):
        self.prior_sd = prior_sd

    def param_spec(self):
        return {"mu": ParamSpec(())}

    def log_prior(self, p):
        return jax.scipy.stats.norm.logpdf(p["mu"], 0.0, self.prior_sd)

    def log_lik(self, p, data):
        return jnp.sum(jax.scipy.stats.norm.logpdf(data["y"], p["mu"], 1.0))


def _posterior_mean_var(y, prior_sd):
    n = y.shape[0]
    prec = 1.0 / prior_sd**2 + n
    return float(y.sum() / prec), float(1.0 / prec)


def test_minibatch_grad_unbiased():
    """E[minibatch grad] == full-data grad (averaged over many keys)."""
    key = jax.random.PRNGKey(0)
    y = 1.5 + jax.random.normal(key, (64,))
    data = {"y": y}
    model = NormalMean()
    fm_full = flatten_model(model)
    fm_mb = flatten_model(model, lik_scale=64 / 8)
    grad_fn = make_minibatch_grad(fm_mb.potential, data, batch_size=8)
    z = jnp.asarray([0.3])
    full = jax.grad(fm_full.potential)(z, data)
    keys = jax.random.split(jax.random.PRNGKey(1), 4000)
    est = jax.vmap(lambda k: grad_fn(k, z))(keys).mean(0)
    np.testing.assert_allclose(np.asarray(est), np.asarray(full), rtol=0.05)


def test_sghmc_step_finite_and_freezes_on_nan():
    inv_mass = jnp.ones(2)
    state = sghmc_init(jax.random.PRNGKey(0), jnp.zeros(2), inv_mass)

    def bad_grad(key, z):
        return jnp.full_like(z, jnp.nan)

    new, info, _ = sghmc_step(
        jax.random.PRNGKey(1), state, bad_grad, jnp.asarray(0.01),
        jnp.asarray(1.0), inv_mass,
    )
    assert bool(info.is_divergent)
    np.testing.assert_array_equal(np.asarray(new.z), np.asarray(state.z))


def test_sghmc_conjugate_normal_posterior():
    key = jax.random.PRNGKey(42)
    n = 512
    y = 2.0 + jax.random.normal(key, (n,))
    data = {"y": y}
    model = NormalMean()
    post = sghmc_sample(
        model,
        data,
        batch_size=64,
        chains=4,
        num_warmup=500,
        num_samples=2000,
        step_size=2e-3,
        friction=5.0,
        resample_every=50,
        seed=3,
    )
    mu_true, var_true = _posterior_mean_var(np.asarray(y), 10.0)
    draws = post.draws["mu"]
    assert post.num_divergent == 0
    assert abs(draws.mean() - mu_true) < 0.05
    # variance within 2x — SGHMC's stationary variance is step-size biased
    assert 0.5 * var_true < draws.var() < 2.0 * var_true


class ScaledNormal(Model):
    """Two independent rows with wildly different posterior scales —
    the shape a unit-mass SG-HMC cannot step efficiently."""

    def param_spec(self):
        return {"a": ParamSpec(()), "b": ParamSpec(())}

    def log_prior(self, p):
        return jnp.zeros(())

    def log_lik(self, p, data):
        # y1 ~ N(a, 0.1), y2 ~ N(b, 5): posterior sds differ 50x
        return jnp.sum(
            jax.scipy.stats.norm.logpdf(data["y1"], p["a"], 0.1)
        ) + jnp.sum(jax.scipy.stats.norm.logpdf(data["y2"], p["b"], 5.0))


@pytest.mark.slow
def test_preconditioning_equilibrates_scales():
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    data = {
        "y1": 1.0 + 0.1 * jax.random.normal(k1, (256,)),
        "y2": -1.0 + 5.0 * jax.random.normal(k2, (256,)),
    }
    kw = dict(
        batch_size=64, chains=4, num_warmup=400, num_samples=1000,
        step_size=2e-3, friction=5.0, seed=1,
    )
    post_pre = sghmc_sample(ScaledNormal(), data, precondition=True, **kw)
    post_unit = sghmc_sample(ScaledNormal(), data, precondition=False, **kw)
    ess_pre = min(float(np.min(v)) for v in post_pre.ess().values())
    ess_unit = min(float(np.min(v)) for v in post_unit.ess().values())
    # unit mass leaves the wide coordinate nearly frozen at eps=2e-3;
    # the adapted mass must recover a usable ESS on BOTH coordinates
    assert ess_pre > 3.0 * ess_unit, (ess_pre, ess_unit)
    # and the location estimates must still be right
    assert abs(float(post_pre.draws["a"].mean()) - 1.0) < 0.05
    assert abs(float(post_pre.draws["b"].mean()) + 1.0) < 1.0


def test_cyclic_schedule_collects_tail_draws():
    key = jax.random.PRNGKey(2)
    y = 1.0 + jax.random.normal(key, (256,))
    post = sghmc_sample(
        NormalMean(), {"y": y}, batch_size=64, chains=2, num_warmup=200,
        num_samples=1000, step_size=2e-3, friction=5.0, seed=0,
        cycles=4, cycle_collect_frac=0.3,
    )
    # 4 cycles of 250 steps, last 30% collected -> 75 per cycle
    assert post.draws["mu"].shape == (2, 300)
    assert np.all(np.isfinite(post.draws["mu"]))
    # still lands on the conjugate posterior
    mu_true, _ = _posterior_mean_var(np.asarray(y), 10.0)
    assert abs(float(post.draws["mu"].mean()) - mu_true) < 0.1


def test_sghmc_on_mesh_chains_axis():
    from stark_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": 2, "chains": 4})
    key = jax.random.PRNGKey(7)
    y = 1.0 + jax.random.normal(key, (128,))
    post = sghmc_sample(
        NormalMean(),
        {"y": y},
        batch_size=32,
        chains=4,
        num_warmup=100,
        num_samples=200,
        step_size=2e-3,
        friction=5.0,
        seed=5,
        mesh=mesh,
    )
    assert post.draws["mu"].shape == (4, 200)
    assert np.all(np.isfinite(post.draws["mu"]))

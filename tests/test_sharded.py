"""Sharded-data execution: logp parity and end-to-end posterior parity
(SURVEY.md §5 'multi-device without a cluster' on the 8-device CPU mesh)."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import stark_tpu
from stark_tpu.compat import shard_map
from stark_tpu.backends.jax_backend import JaxBackend
from stark_tpu.backends.sharded import ShardedBackend
from stark_tpu.model import flatten_model
from stark_tpu.models.logistic import Logistic, synth_logistic_data
from stark_tpu.parallel.mesh import make_mesh, shard_data


@pytest.fixture(scope="module")
def logistic_setup():
    model = Logistic(num_features=4)
    data, _ = synth_logistic_data(jax.random.PRNGKey(0), 2048, 4)
    return model, data


def test_sharded_potential_matches_unsharded(logistic_setup):
    model, data = logistic_setup
    mesh = make_mesh({"data": 8, "chains": 1})
    fm_plain = flatten_model(model)
    fm_shard = flatten_model(model, axis_name="data")
    z = jax.random.normal(jax.random.PRNGKey(1), (fm_plain.ndim,))

    expected = float(fm_plain.potential(z, data))

    specs = jax.tree.map(lambda _: P("data"), data)
    fn = shard_map(
        lambda zz, dd: fm_shard.potential(zz, dd),
        mesh=mesh,
        in_specs=(P(), specs),
        out_specs=P(),
        check_vma=False,
    )
    got = float(jax.jit(fn)(z, shard_data(data, mesh)))
    np.testing.assert_allclose(got, expected, rtol=2e-5)


@pytest.mark.slow
def test_sharded_backend_matches_jax_backend(logistic_setup):
    model, data = logistic_setup
    mesh = make_mesh({"data": 2, "chains": 4})
    post_sharded = stark_tpu.sample(
        model, data, backend=ShardedBackend(mesh), chains=4,
        num_warmup=300, num_samples=300, seed=0,
    )
    post_plain = stark_tpu.sample(
        model, data, backend=JaxBackend(), chains=4,
        num_warmup=300, num_samples=300, seed=0,
    )
    assert post_sharded.max_rhat() < 1.05
    b_sh = post_sharded.summary()["beta"]
    b_pl = post_plain.summary()["beta"]
    # same posterior within MC error
    np.testing.assert_allclose(b_sh["mean"], b_pl["mean"], atol=0.05)
    np.testing.assert_allclose(b_sh["sd"], b_pl["sd"], rtol=0.35, atol=0.01)


@pytest.mark.slow
def test_sharded_backend_no_data_model():
    from stark_tpu.models.eight_schools import EightSchools, eight_schools_data

    # chains-only mesh; the model's data rows (8) don't divide 8 devices'
    # data axis, so run it replicated with data folded into chains axis
    mesh = make_mesh({"data": 1, "chains": 8})
    post = stark_tpu.sample(
        EightSchools(), eight_schools_data(), backend=ShardedBackend(mesh),
        chains=8, num_warmup=300, num_samples=200, seed=0,
    )
    mu = float(post.summary()["mu"]["mean"])
    assert 2.0 < mu < 7.0


def test_chains_not_divisible_raises():
    mesh = make_mesh({"data": 2, "chains": 4})
    with pytest.raises(ValueError, match="chains"):
        stark_tpu.sample(
            Logistic(2), {"x": jnp.zeros((16, 2)), "y": jnp.zeros(16)},
            backend=ShardedBackend(mesh), chains=3, num_warmup=10, num_samples=10,
        )


def test_rows_not_divisible_raises(logistic_setup):
    model, _ = logistic_setup
    mesh = make_mesh({"data": 8, "chains": 1})
    bad = {"x": jnp.zeros((2047, 4)), "y": jnp.zeros(2047)}
    with pytest.raises(ValueError, match="divisible"):
        stark_tpu.sample(
            model, bad, backend=ShardedBackend(mesh), chains=1,
            num_warmup=10, num_samples=10,
        )


def test_sharded_chees_transition_matches_unsharded(logistic_setup):
    """One ensemble transition with chains sharded over the mesh must equal
    the unsharded transition (per-chain-id RNG; cross-chain reductions as
    collectives), up to reduction-order float error."""
    from stark_tpu.kernels.chees import chees_transition, init_ensemble

    model, data = logistic_setup
    fm = flatten_model(model)
    C = 8
    potential_fn = fm.bind(data)
    z0 = jax.vmap(fm.init_flat)(jax.random.split(jax.random.PRNGKey(2), C))
    states = init_ensemble(potential_fn, z0)
    key = jax.random.PRNGKey(3)
    eps = jnp.asarray(0.05)
    inv_mass = jnp.ones((fm.ndim,))
    L = jnp.asarray(7, jnp.int32)

    ref_states, ref_info = jax.jit(
        lambda k, s: chees_transition(k, s, potential_fn, eps, inv_mass, L)
    )(key, states)

    from stark_tpu.kernels.chees import CheesInfo

    mesh = make_mesh({"data": 1, "chains": 8})
    info_spec = CheesInfo(
        accept_prob=P("chains"), is_accepted=P("chains"),
        is_divergent=P("chains"), grad_rel_T=P(), num_leapfrog=P(),
    )
    sharded = shard_map(
        lambda k, s: chees_transition(
            k, s, potential_fn, eps, inv_mass, L, chains_axis="chains"
        ),
        mesh=mesh,
        in_specs=(P(), P("chains")),
        out_specs=(P("chains"), info_spec),
        check_vma=False,
    )
    sh_states, sh_info = jax.jit(sharded)(key, states)

    np.testing.assert_allclose(
        np.asarray(sh_states.z), np.asarray(ref_states.z), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(sh_info.accept_prob), np.asarray(ref_info.accept_prob),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        float(sh_info.grad_rel_T), float(ref_info.grad_rel_T),
        rtol=1e-3, atol=1e-5,
    )


@pytest.mark.slow
def test_sharded_chees_backend_matches_jax_backend(logistic_setup):
    """Full sharded ChEES run (data x chains mesh) reaches the same
    posterior as the single-device ensemble — distribution-level parity."""
    model, data = logistic_setup
    mesh = make_mesh({"data": 2, "chains": 4})
    post_sharded = stark_tpu.sample(
        model, data, backend=ShardedBackend(mesh), chains=8,
        kernel="chees", num_warmup=300, num_samples=300,
        init_step_size=0.1, seed=0,
    )
    post_plain = stark_tpu.sample(
        model, data, backend=JaxBackend(), chains=8,
        kernel="chees", num_warmup=300, num_samples=300,
        init_step_size=0.1, seed=0,
    )
    assert post_sharded.max_rhat() < 1.05
    assert post_plain.max_rhat() < 1.05
    for k in post_sharded.draws:
        m_s = np.mean(post_sharded.draws[k], axis=(0, 1))
        m_p = np.mean(post_plain.draws[k], axis=(0, 1))
        sd = np.std(post_plain.draws[k], axis=(0, 1))
        np.testing.assert_allclose(m_s, m_p, atol=4 * np.max(sd) / np.sqrt(300))


@pytest.mark.slow
def test_sharded_chees_dispatch_bounded(logistic_setup):
    """dispatch_steps segments the sharded chees run without changing the
    draw count or convergence."""
    model, data = logistic_setup
    mesh = make_mesh({"data": 4, "chains": 2})
    post = stark_tpu.sample(
        model, data, backend=ShardedBackend(mesh, dispatch_steps=50),
        chains=4, kernel="chees", num_warmup=120, num_samples=80,
        init_step_size=0.1, seed=1,
    )
    assert post.num_samples == 80
    assert np.isfinite(post.draws_flat).all()


def _coxph_tied_setup(n=2048, d=3, seed=0):
    """Survival data whose tie blocks SPAN shard boundaries: times drawn
    from a small value set (runs ~50 long at 256-row shards) plus one
    600-row mega-tie that swallows multiple whole shards — the worst
    case for the cross-shard tie stitching."""
    from stark_tpu.models import CoxPH

    rng = np.random.RandomState(seed)
    t = rng.randint(0, 37, size=n).astype(np.float32)
    t[100:700] = 50.0  # mega tie-run spanning shards
    data = {
        "x": rng.randn(n, d).astype(np.float32),
        "t": t,
        "event": (rng.rand(n) < 0.7).astype(np.float32),
    }
    model = CoxPH(num_features=d)
    return model, model.prepare_data(data)


@pytest.mark.slow  # >=8s on the 1-core host (pytest.ini policy, re-profiled 2026-08-03)
def test_coxph_sharded_potential_and_grad_match_unsharded():
    """Sequence-parallel CoxPH (r5): the cross-shard prefix-logsumexp +
    tie stitching in log_lik_sharded reproduces the unsharded Breslow
    potential AND gradient on the 8-device mesh to f32 roundoff —
    including tie blocks that span one or several shard boundaries."""
    from stark_tpu.parallel.mesh import row_partition_specs

    model, data = _coxph_tied_setup()
    mesh = make_mesh({"data": 8, "chains": 1})
    fm_plain = flatten_model(model)
    fm_shard = flatten_model(model, axis_name="data")
    z = 0.3 * jax.random.normal(jax.random.PRNGKey(1), (fm_plain.ndim,))

    v_exp, g_exp = jax.jit(fm_plain.potential_and_grad)(z, data)

    row_axes = model.data_shard_row_axes(data)
    specs = row_partition_specs(data, "data", row_axes)
    fn = shard_map(
        lambda zz, dd: fm_shard.potential_and_grad(zz, dd),
        mesh=mesh,
        in_specs=(P(), specs),
        out_specs=(P(), P()),
        check_vma=False,
    )
    v_got, g_got = jax.jit(fn)(
        z, shard_data(data, mesh, row_axes=row_axes)
    )
    np.testing.assert_allclose(float(v_got), float(v_exp), rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(g_got), np.asarray(g_exp), rtol=2e-4, atol=1e-4
    )


def test_coxph_minibatch_paths_still_fail_fast():
    """Mesh sharding is supported, but minibatching / sub-posterior
    splits consult data_row_axes and must STILL refuse CoxPH."""
    model, data = _coxph_tied_setup(n=256)
    with pytest.raises(NotImplementedError, match="minibatched"):
        model.data_row_axes(data)
    axes = model.data_shard_row_axes(data)  # the mesh path works
    assert all(a == 0 for a in jax.tree.leaves(axes))


@pytest.mark.slow
def test_coxph_sharded_backend_end_to_end():
    """ShardedBackend NUTS on CoxPH over the data axis converges and
    matches the single-device posterior (same seed)."""
    from stark_tpu.models import CoxPH, synth_survival_data

    data, true = synth_survival_data(jax.random.PRNGKey(0), 1024, 3)
    mesh = make_mesh({"data": 4, "chains": 2})
    post_s = stark_tpu.sample(
        CoxPH(num_features=3), data, backend=ShardedBackend(mesh),
        chains=2, kernel="nuts", max_tree_depth=6, num_warmup=200,
        num_samples=200, seed=0,
    )
    post_p = stark_tpu.sample(
        CoxPH(num_features=3), data, backend=JaxBackend(),
        chains=2, kernel="nuts", max_tree_depth=6, num_warmup=200,
        num_samples=200, seed=0,
    )
    assert post_s.max_rhat() < 1.05
    bs = np.asarray(post_s.draws["beta"]).mean(axis=(0, 1))
    bp = np.asarray(post_p.draws["beta"]).mean(axis=(0, 1))
    np.testing.assert_allclose(bs, bp, atol=0.15)
    np.testing.assert_allclose(bs, np.asarray(true["beta"]), atol=0.4)


def test_sv_sharded_potential_and_grad_match_unsharded():
    """Sequence-parallel StochasticVolatility (r5): each shard slices its
    time block from the replicated latent path; sharded potential and
    gradient match the unsharded model on the 8-device mesh."""
    from stark_tpu.models.timeseries import StochasticVolatility, synth_sv_data
    from stark_tpu.parallel.mesh import row_partition_specs

    model = StochasticVolatility(num_steps=512)
    data, _ = synth_sv_data(jax.random.PRNGKey(0), 512)
    mesh = make_mesh({"data": 8, "chains": 1})
    fm_plain = flatten_model(model)
    fm_shard = flatten_model(model, axis_name="data")
    z = 0.2 * jax.random.normal(jax.random.PRNGKey(1), (fm_plain.ndim,))

    v_exp, g_exp = jax.jit(fm_plain.potential_and_grad)(z, data)

    row_axes = model.data_shard_row_axes(data)
    specs = row_partition_specs(data, "data", row_axes)
    fn = shard_map(
        lambda zz, dd: fm_shard.potential_and_grad(zz, dd),
        mesh=mesh,
        in_specs=(P(), specs),
        out_specs=(P(), P()),
        check_vma=False,
    )
    v_got, g_got = jax.jit(fn)(
        z, shard_data(data, mesh, row_axes=row_axes)
    )
    np.testing.assert_allclose(float(v_got), float(v_exp), rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(g_got), np.asarray(g_exp), rtol=2e-4, atol=1e-4
    )
    # minibatch paths still refuse
    with pytest.raises(NotImplementedError, match="minibatched"):
        model.data_row_axes(data)


def test_sv_sharded_length_mismatch_fails_fast():
    """A num_steps/data-length mismatch must fail at trace time — the
    clamping semantics of dynamic_slice would otherwise evaluate several
    shards against the same tail slice of a too-short latent path."""
    from stark_tpu.models.timeseries import StochasticVolatility, synth_sv_data

    model = StochasticVolatility(num_steps=256)
    data, _ = synth_sv_data(jax.random.PRNGKey(0), 512)
    mesh = make_mesh({"data": 8, "chains": 1})
    with pytest.raises(ValueError, match="must[\\s\\S]*match exactly"):
        stark_tpu.sample(
            model, data, backend=ShardedBackend(mesh), chains=1,
            kernel="nuts", max_tree_depth=4, num_warmup=4, num_samples=4,
            seed=0,
        )


# ---------------------------------------------------------------------------
# scan_shards migration bit-identity (PR 19): the sequence-parallel
# stitching moved off hand-rolled gathers onto the ordered-scan
# primitive; each combine keeps the models' exact masked arithmetic, so
# the migration must be DRAW-bit-identical, pinned here against the
# pre-migration implementations copied verbatim below.
# ---------------------------------------------------------------------------


def _legacy_coxph_log_lik_sharded(model, p, data, axis_name):
    """The pre-scan_shards CoxPH stitching (hand-rolled gather_axis +
    shard-index masks), kept as the bit-identity reference."""
    from stark_tpu.models.survival import (
        _cumulative_logsumexp,
        _fill_from_right_valid,
    )
    from stark_tpu.parallel.primitives import gather_axis, mapped_axis_size

    eta = data["x"] @ p["beta"]
    t = data["t"]
    s = jax.lax.axis_index(axis_name)
    num_shards = mapped_axis_size(axis_name)
    prefix_l = _cumulative_logsumexp(eta)
    totals = gather_axis(prefix_l[-1], axis_name)
    firsts = gather_axis(t[0], axis_name)
    carry = jax.scipy.special.logsumexp(
        jnp.where(jnp.arange(num_shards) < s, totals, -jnp.inf)
    )
    prefix_g = jnp.logaddexp(prefix_l, carry)
    nxt = firsts[jnp.minimum(s + 1, num_shards - 1)]
    last_is_end = jnp.where(s + 1 < num_shards, t[-1] != nxt, True)
    is_end = jnp.concatenate([t[1:] != t[:-1], last_is_end[None]])
    fill, has_end = _fill_from_right_valid(prefix_g, is_end)
    g2 = gather_axis(
        jnp.stack([fill[0], has_end[0].astype(eta.dtype)]), axis_name
    )
    fs, hs = g2[:, 0], g2[:, 1] > 0.5
    later = jnp.arange(num_shards) > s
    rfill, _ = _fill_from_right_valid(
        jnp.where(later, fs, 0.0), later & hs
    )
    log_risk = jnp.where(has_end, fill, rfill[0])
    return jnp.sum(data["event"] * (eta - log_risk))


def _legacy_sv_log_lik_sharded(model, p, data, axis_name):
    """The pre-scan_shards SV slice (hand-rolled dynamic_slice by shard
    index), kept as the bit-identity reference."""
    from stark_tpu.parallel.primitives import mapped_axis_size

    h = model.latent_h(p)
    m = data["y"].shape[0]
    num_shards = mapped_axis_size(axis_name)
    assert m * num_shards == model.num_steps
    s = jax.lax.axis_index(axis_name)
    h_loc = jax.lax.dynamic_slice_in_dim(h, s * m, m)
    import jax.scipy.stats as jstats

    return jnp.sum(
        jstats.norm.logpdf(data["y"], 0.0, jnp.exp(h_loc / 2.0))
    )


def _bitwise_vs_legacy(model, data, legacy_log_lik, shards=4):
    """Potential AND gradient of the migrated sharded path, bitwise
    against the hand-rolled reference on the same mesh."""
    from stark_tpu.parallel.mesh import row_partition_specs

    mesh = make_mesh(
        {"data": shards, "chains": 1}, devices=jax.devices()[:shards]
    )
    fm = flatten_model(model, axis_name="data")
    z = 0.3 * jax.random.normal(jax.random.PRNGKey(1), (fm.ndim,))
    row_axes = model.data_shard_row_axes(data)
    specs = row_partition_specs(data, "data", row_axes)
    sharded = shard_data(data, mesh, row_axes=row_axes)

    def run(fmodel):
        fn = shard_map(
            lambda zz, dd: fmodel.potential_and_grad(zz, dd),
            mesh=mesh, in_specs=(P(), specs), out_specs=(P(), P()),
            check_vma=False,
        )
        v, g = jax.jit(fn)(z, sharded)
        return np.asarray(v), np.asarray(g)

    class _Legacy(type(model)):
        def log_lik_sharded(self, p, d, axis_name):
            return legacy_log_lik(self, p, d, axis_name)

    legacy = _Legacy.__new__(_Legacy)
    legacy.__dict__.update(model.__dict__)
    fm_legacy = flatten_model(legacy, axis_name="data")

    v_new, g_new = run(fm)
    v_old, g_old = run(fm_legacy)
    np.testing.assert_array_equal(v_new, v_old)
    np.testing.assert_array_equal(g_new, g_old)


def test_coxph_scan_shards_migration_bit_identical():
    """CoxPH's three-scan stitching on `scan_shards` reproduces the
    hand-rolled gathers to the BYTE (value and gradient), including tie
    blocks spanning shard boundaries."""
    model, data = _coxph_tied_setup(n=1024, d=3)
    _bitwise_vs_legacy(
        model, data, _legacy_coxph_log_lik_sharded, shards=4
    )


def test_sv_scan_shards_migration_bit_identical():
    """SV's replicated-path slice via scan_shards(replicated=True) is
    byte-identical to the hand-rolled dynamic_slice."""
    from stark_tpu.models import StochasticVolatility
    from stark_tpu.models.timeseries import synth_sv_data

    model = StochasticVolatility(num_steps=512)
    data, _ = synth_sv_data(jax.random.PRNGKey(2), 512)
    _bitwise_vs_legacy(model, data, _legacy_sv_log_lik_sharded, shards=4)

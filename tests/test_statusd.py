"""Live run-health exporter: endpoint contracts and the stall drill.

Fast tier: endpoint behavior against synthetic trace events (healthz
503 flip + recovery, parseable /metrics, /status snapshot, default-off).
Slow tier: the REAL chaos stall scenario — a supervised run with an
injected 60 s stall, scraped concurrently: /healthz must flip to 503
when the watchdog fires and recover to 200 after the supervisor
restart, and the monotone counters must never step backwards across
the attempts.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from stark_tpu import telemetry
from stark_tpu.statusd import StatusServer, maybe_start_from_env, resolve_port

from test_metrics import parse_exposition


def _get(port, path):
    """(status_code, body_text) without raising on 4xx/5xx."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture
def server():
    srv = StatusServer(0, host="127.0.0.1").start()
    yield srv
    srv.stop()


def test_endpoints_serve_metrics_status_healthz(server):
    tr = telemetry.RunTrace(None)
    tr.emit("run_start", entry="sample", model="M", kernel="hmc", chains=2,
            git_sha="abc123")
    tr.emit("sample_block", block=2, dur_s=0.1, block_len=25,
            draws_per_chain=50, ess_forecast=120)
    code, text = _get(server.port, "/metrics")
    assert code == 200
    samples, types = parse_exposition(text)
    assert samples["stark_runs_started_total"] == 1
    assert samples['stark_blocks_total{phase="sample"}'] == 1
    assert types["stark_draws_total"] == "counter"
    code, body = _get(server.port, "/healthz")
    assert code == 200 and body == "ok\n"
    code, body = _get(server.port, "/status")
    assert code == 200
    snap = json.loads(body)
    assert snap["phase"] == "sample" and snap["block"] == 2
    assert snap["ess_forecast"] == 120
    assert snap["meta"]["git_sha"] == "abc123"
    assert _get(server.port, "/nope")[0] == 404


def test_healthz_flips_on_stall_and_recovers_on_restart(server):
    tr = telemetry.RunTrace(None)
    tr.emit("run_start", model="M", chains=2)
    assert _get(server.port, "/healthz")[0] == 200
    # the watchdog's stall event (what Watchdog._watch emits)
    tr.emit("chain_health", status="stall", deadline_s=3.0, idle_s=3.2,
            stall_count=1)
    code, body = _get(server.port, "/healthz")
    assert code == 503 and json.loads(body)["reason"] == "stall"
    # the supervisor records the failed attempt…
    tr.emit("chain_health", status="restart", attempt=1, fault="stall",
            restarts_in_window=1, max_restarts=3)
    assert _get(server.port, "/healthz")[0] == 503
    # …and the next attempt's run_start is the recovery signal
    tr.emit("run_start", model="M", chains=2)
    assert _get(server.port, "/healthz")[0] == 200
    # budget exhaustion is terminal: no later event recovers it
    tr.emit("chain_health", status="restart_budget_exhausted",
            restarts_in_window=4, max_restarts=3)
    tr.emit("run_start", model="M", chains=2)
    code, body = _get(server.port, "/healthz")
    assert code == 503
    assert json.loads(body)["reason"] == "restart_budget_exhausted"


def test_degraded_fleet_stays_200_with_status_surfaced(server):
    """The degraded-fleet /healthz policy: lane quarantines are a
    per-tenant loss, not process unhealth — /healthz stays 200 while
    /status and /metrics surface the degradation; 503 stays reserved
    for process-level events (a stall still flips it)."""
    tr = telemetry.RunTrace(None)
    tr.emit("run_start", entry="sample_fleet", problems=3, chains=2)
    tr.emit("problem_reseeded", problem_id="p1", fault="poisoned_state",
            lane_restarts=1, max_restarts=1)
    tr.emit("problem_quarantined", problem_id="p1",
            status="failed:poisoned_state", fault="poisoned_state",
            reason="non-finite z", lane_restarts=2)
    code, body = _get(server.port, "/healthz")
    assert code == 200 and body == "ok\n"
    code, body = _get(server.port, "/status")
    snap = json.loads(body)
    assert snap["healthy"] is True
    assert snap["fleet"]["degraded"] is True
    assert snap["fleet"]["lost_problems"] == ["p1"]
    assert snap["fleet"]["last_quarantined"]["fault"] == "poisoned_state"
    code, text = _get(server.port, "/metrics")
    samples, _types = parse_exposition(text)
    assert samples["stark_fleet_degraded"] == 1
    assert samples["stark_fleet_lane_reseeds_total"] == 1
    assert samples["stark_fleet_problems_quarantined_total"] == 1
    # process-level unhealth still flips 503, degraded or not
    tr.emit("chain_health", status="stall", deadline_s=3.0, idle_s=3.2,
            stall_count=1)
    assert _get(server.port, "/healthz")[0] == 503


def test_status_contract_schema_and_uptime(server):
    """The /status machine contract (PR 11): a ``schema`` version
    stamp, a monotone ``uptime_s``, and a ``last_postmortem`` slot —
    consumers key on ``schema`` before trusting the rest."""
    from stark_tpu.metrics import STATUS_SCHEMA

    code, body = _get(server.port, "/status")
    assert code == 200
    snap = json.loads(body)
    assert snap["schema"] == STATUS_SCHEMA == 4
    assert isinstance(snap["uptime_s"], (int, float))
    assert snap["uptime_s"] >= 0
    assert "last_postmortem" in snap
    time.sleep(0.05)
    later = json.loads(_get(server.port, "/status")[1])
    assert later["uptime_s"] > snap["uptime_s"]


def test_status_cli_json_envelope(server, capsys):
    """``stark_tpu status --json``: one machine-parseable line,
    {"endpoint", "code", "body"} with the body parsed when it was JSON
    — for /status, /healthz (both polarities), and /metrics."""
    from stark_tpu.__main__ import main as cli_main

    port = str(server.port)
    assert cli_main(["status", "--port", port, "--json"]) == 0
    out = capsys.readouterr().out
    assert out.count("\n") == 1
    env = json.loads(out)
    assert env["endpoint"] == "status" and env["code"] == 200
    assert env["body"]["schema"] == 4

    assert cli_main(["status", "--port", port, "--healthz", "--json"]) == 0
    env = json.loads(capsys.readouterr().out)
    assert env["endpoint"] == "healthz" and env["code"] == 200
    assert env["body"] == "ok\n"

    # flip unhealthy: the 503 body is JSON and must arrive parsed
    telemetry.RunTrace(None).emit(
        "chain_health", status="stall", deadline_s=1.0, idle_s=2.0,
        stall_count=1,
    )
    assert cli_main(["status", "--port", port, "--healthz", "--json"]) == 1
    env = json.loads(capsys.readouterr().out)
    assert env["code"] == 503
    assert env["body"]["healthy"] is False
    # recover for other tests sharing the fixture pattern
    telemetry.RunTrace(None).emit("run_start", entry="t")

    assert cli_main(["status", "--port", port, "--metrics", "--json"]) == 0
    env = json.loads(capsys.readouterr().out)
    assert env["endpoint"] == "metrics"
    assert isinstance(env["body"], str) and "stark_" in env["body"]

    # without --json the raw body contract is unchanged
    assert cli_main(["status", "--port", port]) == 0
    assert json.loads(capsys.readouterr().out)["schema"] == 4


def test_status_cli_json_envelope_when_nothing_listens(capsys):
    """The one-line contract holds with no exporter: code null, the
    error in its own slot, exit 2 unchanged."""
    from stark_tpu.__main__ import main as cli_main

    # an ephemeral bound-then-closed port: guaranteed refusal
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead = s.getsockname()[1]
    s.close()
    assert cli_main(["status", "--port", str(dead), "--json"]) == 2
    out = capsys.readouterr().out
    assert out.count("\n") == 1
    env = json.loads(out)
    assert env["endpoint"] == "status"
    assert env["code"] is None and env["body"] is None
    assert env["error"]
    # without --json: stdout stays empty (the historical contract)
    assert cli_main(["status", "--port", str(dead)]) == 2
    assert capsys.readouterr().out == ""


def test_off_by_default_no_thread_no_listener(monkeypatch):
    """The zero-cost contract: port unset → no server thread, no event
    listener, and a traced run writes byte-wise the same event shapes."""
    monkeypatch.delenv("STARK_STATUS_PORT", raising=False)
    assert resolve_port(None) is None
    assert maybe_start_from_env(None) is None
    assert not [
        t for t in threading.enumerate()
        if t.name.startswith("stark-statusd")
    ]
    assert not telemetry._EVENT_LISTENERS


def test_cli_port_starts_and_singleton():
    from stark_tpu import statusd

    # an explicit CLI --status-port 0 requests an ephemeral bind
    srv = maybe_start_from_env(0)
    try:
        assert srv is not None and srv.port is not None
        # second call (e.g. bench.py under the CLI) reuses the daemon
        assert maybe_start_from_env(0) is srv
        assert _get(srv.port, "/healthz")[0] == 200
    finally:
        statusd.stop_status_server()
    assert statusd.get_server() is None


def test_env_port_zero_or_invalid_disables(monkeypatch):
    # =0 opts out, the repo-wide env convention (STARK_PERF_LEDGER etc.):
    # a nested job must be able to disable a CI-exported port
    monkeypatch.setenv("STARK_STATUS_PORT", "0")
    assert resolve_port(None) is None
    assert maybe_start_from_env(None) is None
    monkeypatch.setenv("STARK_STATUS_PORT", "not-a-port")
    assert resolve_port(None) is None
    assert maybe_start_from_env(None) is None


def test_trace_file_bytes_unaffected_by_exporter(tmp_path):
    """The exporter observes the trace, never mutates it: the same emit
    sequence writes records with identical keys and identical non-clock
    values whether or not a collector is listening."""

    def run_one(path, with_server):
        srv = StatusServer(0, host="127.0.0.1").start() if with_server else None
        tr = telemetry.RunTrace(str(path))
        tr.emit("run_start", model="M", kernel="hmc", chains=2)
        tr.emit("sample_block", block=1, dur_s=0.5, block_len=25)
        tr.emit("run_end", dur_s=1.0, converged=True)
        tr.close()
        if srv is not None:
            srv.stop()
        return telemetry.read_trace(str(path))

    plain = run_one(tmp_path / "plain.jsonl", with_server=False)
    served = run_one(tmp_path / "served.jsonl", with_server=True)
    clock_keys = {"ts", "wall_s"}
    assert len(plain) == len(served)
    for a, b in zip(plain, served):
        assert set(a) == set(b)
        for k in set(a) - clock_keys:
            assert a[k] == b[k], k


def test_scrape_error_returns_500_not_crash(server):
    """A poisoned registry must 500 the one request, not kill the daemon."""
    server.collector.registry.render = lambda: 1 / 0  # type: ignore[assignment]
    code, _ = _get(server.port, "/metrics")
    assert code == 500
    assert _get(server.port, "/healthz")[0] == 200  # daemon still alive


# ---------------------------------------------------------------------------
# the real thing: supervised stall chaos drill scraped live (slow tier,
# same policy as chaos.py's stall_watchdog scenario)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_supervised_stall_drill_healthz_flip(tmp_path):
    import jax.numpy as jnp

    from stark_tpu import faults
    from stark_tpu.model import Model, ParamSpec
    from stark_tpu.supervise import supervised_sample

    class StdNormal(Model):
        def param_spec(self):
            return {"x": ParamSpec((2,))}

        def log_prior(self, p):
            return -0.5 * jnp.sum(p["x"] ** 2)

        def log_lik(self, p, data):
            return jnp.zeros(())

    srv = StatusServer(0, host="127.0.0.1").start()
    seen = []  # (t, healthz_code, blocks_total) samples
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            code, _ = _get(srv.port, "/healthz")
            text = _get(srv.port, "/metrics")[1]
            samples, _types = parse_exposition(text)
            seen.append(
                (code, samples.get('stark_blocks_total{phase="sample"}', 0.0))
            )
            time.sleep(0.05)

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    faults.reset()
    faults.configure("runner.block.pre=stall(60)*1@1")
    try:
        # what the CLI's --status-port does when no --trace was given: an
        # in-memory bus trace so the exporter sees the run's events
        # (NullTrace would starve the collector — and the watchdog's
        # stall event with it)
        with telemetry.use_trace(telemetry.RunTrace(None)):
            # deadline sized for this 1-core host: a first-block compile
            # above the deadline would false-positive the watchdog (the
            # documented "longer than the worst single dispatch including
            # its compile" rule) — the injected stall is 60 s, so 8 s
            # still detects it 7x faster while staying clear of compile
            post = supervised_sample(
                StdNormal(), workdir=str(tmp_path), seed=0,
                stall_timeout_s=8.0, max_restarts=5, chains=2,
                block_size=25, max_blocks=8, min_blocks=2,
                rhat_target=10.0, ess_target=1.0, num_warmup=40,
                kernel="hmc", num_leapfrog=8,
            )
    finally:
        faults.reset()
        stop.set()
        poller.join(timeout=5)
    assert post is not None
    codes = [c for c, _ in seen]
    assert 503 in codes, "healthz never flipped during the stall"
    # the run finished: the final state must be recovered
    assert _get(srv.port, "/healthz")[0] == 200
    # monotone counters across the restart: never a backward step
    blocks = [b for _, b in seen]
    assert all(b2 >= b1 for b1, b2 in zip(blocks, blocks[1:]))
    samples, _types = parse_exposition(_get(srv.port, "/metrics")[1])
    assert samples['stark_restarts_total{fault="stall"}'] >= 1
    assert samples["stark_stalls_total"] >= 1
    assert samples["stark_runs_started_total"] >= 2
    srv.stop()

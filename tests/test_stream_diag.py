"""On-device streaming diagnostics + ESS-forecast adaptive block scheduler.

The tentpole contracts (runner.py / kernels/base.py / diagnostics.py):

* `ess_from_suffstats` is a conservative (lower-bound-leaning) estimate of
  the full-history Geyer ESS, computed from O(chains*d*L) accumulators;
* the device scan's `StreamDiagState` matches the host reference rebuild
  (`stream_diag_from_draws`) — the resume path depends on that;
* the streaming accumulator never perturbs the draw stream: stream-on and
  stream-off runs produce bit-identical draws/checkpoints/stores;
* `STARK_STREAM_DIAG=0 STARK_ADAPTIVE_BLOCKS=0` restores the historical
  fixed-block runner bit-exactly (the escape hatches);
* the convergence gate's host transfer is CONSTANT O(chains*d*L) per block
  with streaming on (``diag_bytes_to_host`` trace field);
* adaptive scheduling converges in fewer post-warmup draws than the fixed
  march on the eight-schools benchmark at equal targets;
* the streaming gate can NEVER stop a run the full-pass validation rejects
  (drilled via the ``runner.gate.optimistic`` failpoint).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import stark_tpu
from stark_tpu import diagnostics, faults
from stark_tpu.checkpoint import load_checkpoint
from stark_tpu.kernels.base import (
    STREAM_DIAG_LAGS,
    stream_diag_init,
    stream_diag_update,
)
from stark_tpu.model import Model, ParamSpec
from stark_tpu.telemetry import RunTrace, read_trace, summarize_trace

_DIAG_FIELDS = ("n", "anchor", "s1", "s2", "cross", "ring", "head")


class StdNormal2(Model):
    def param_spec(self):
        return {"x": ParamSpec((2,))}

    def log_prior(self, p):
        return -0.5 * jnp.sum(p["x"] ** 2)

    def log_lik(self, p, data):
        return jnp.zeros(())


def _ar1(rng, phi, chains, n, d, mean=5.0):
    x = np.zeros((chains, n, d))
    innov = rng.standard_normal((chains, n, d))
    for t in range(1, n):
        x[:, t] = phi * x[:, t - 1] + innov[:, t] * np.sqrt(1 - phi**2)
    return x + mean


def _stream_ess(draws, lags=STREAM_DIAG_LAGS):
    st = diagnostics.stream_diag_from_draws(
        np.asarray(draws, np.float32), lags
    )
    return diagnostics.ess_from_suffstats(*[st[k] for k in _DIAG_FIELDS])


def test_ess_from_suffstats_tracks_full_ess_on_ar1():
    """Across AR(1) autocorrelation regimes the streaming estimator tracks
    the full-history Geyer ESS within tolerance, and never exceeds it by
    more than estimator noise — it must err LOW (the gate waits), never
    report a chain healthier than the full pass would."""
    rng = np.random.default_rng(0)
    for phi in (0.0, 0.3, 0.6, 0.9):
        x = _ar1(rng, phi, chains=4, n=2000, d=3)
        full = diagnostics.ess(x)
        stream = _stream_ess(x)
        assert np.all(np.isfinite(stream)), (phi, stream)
        # within-tolerance agreement when the autocorrelation resolves
        # inside the tracked lags (tau <= ~19 at phi=0.9, L=50)
        np.testing.assert_allclose(stream, full, rtol=0.15,
                                   err_msg=f"phi={phi}")
        assert np.all(stream <= full * 1.15), (phi, stream, full)


def test_ess_from_suffstats_conservative_when_truncated():
    """tau > L regime: the Geyer pair sequence cannot terminate inside the
    tracked lags, so the geometric tail extension must keep the estimate
    at or below the full-history value — the truncation bias direction is
    DOWN (conservative), so a slow-mixing run keeps sampling."""
    rng = np.random.default_rng(1)
    x = _ar1(rng, 0.99, chains=4, n=2000, d=3)  # tau ~ 199 >> L=50
    full = diagnostics.ess(x)
    stream = _stream_ess(x)
    assert np.all(stream <= full * 1.1), (stream, full)


def test_ess_from_suffstats_frozen_component_nan():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((3, 500, 2))
    x[:, :, 1] = 7.0  # frozen everywhere
    stream = _stream_ess(x)
    assert np.isfinite(stream[0])
    assert np.isnan(stream[1])


def test_device_accumulator_matches_host_reference():
    """The compiled scan's StreamDiagState == stream_diag_from_draws on
    the same draws (to roundoff) — the resume path rebuilds the device
    carry with the host reference, so they must be the same math."""
    rng = np.random.default_rng(3)
    draws = (rng.standard_normal((3, 37, 5)) * 2 + 1).astype(np.float32)
    lags = 8

    def run_chain(xs):
        def body(s, x):
            return stream_diag_update(s, x), None

        s, _ = jax.lax.scan(body, stream_diag_init(5, lags), xs)
        return s

    dev = jax.vmap(run_chain)(jnp.asarray(draws))
    host = diagnostics.stream_diag_from_draws(draws, lags)
    for k in _DIAG_FIELDS:
        np.testing.assert_allclose(
            np.asarray(getattr(dev, k)), host[k], rtol=2e-4, atol=2e-4,
            err_msg=k,
        )
    e_dev = diagnostics.ess_from_suffstats(
        *[np.asarray(getattr(dev, k)) for k in _DIAG_FIELDS]
    )
    e_host = diagnostics.ess_from_suffstats(*[host[k] for k in _DIAG_FIELDS])
    np.testing.assert_allclose(e_dev, e_host, rtol=1e-3)


def _run(tmp_path, tag, **kw):
    d = tmp_path / tag
    d.mkdir()
    paths = {
        "ckpt": str(d / "c.npz"),
        "store": str(d / "d.stkr"),
        "metrics": str(d / "m.jsonl"),
    }
    post = stark_tpu.sample_until_converged(
        StdNormal2(),
        checkpoint_path=paths["ckpt"],
        draw_store_path=paths["store"],
        metrics_path=paths["metrics"],
        **kw,
    )
    return post, paths


_KW = dict(chains=2, block_size=20, max_blocks=3, min_blocks=3,
           rhat_target=0.0, num_warmup=30, kernel="hmc", num_leapfrog=4,
           seed=0)


def test_stream_on_off_draw_identity(tmp_path):
    """The accumulator only CONSUMES the draw stream: with fixed blocks,
    stream-on and stream-off runs produce bit-identical draws, checkpoint
    arrays, and draw-store bytes (only the gate's min_ess source and the
    new metrics fields differ)."""
    on, p_on = _run(tmp_path, "on", stream_diag=True,
                    adaptive_blocks=False, **_KW)
    off, p_off = _run(tmp_path, "off", stream_diag=False,
                      adaptive_blocks=False, **_KW)
    np.testing.assert_array_equal(on.draws_flat, off.draws_flat)
    a_on, _ = load_checkpoint(p_on["ckpt"])
    a_off, _ = load_checkpoint(p_off["ckpt"])
    assert set(a_on) == set(a_off)
    for k in a_on:
        np.testing.assert_array_equal(a_on[k], a_off[k], err_msg=k)
    with open(p_on["store"], "rb") as f:
        b_on = f.read()
    with open(p_off["store"], "rb") as f:
        b_off = f.read()
    assert b_on == b_off
    # the new metrics fields ride ONLY the streaming mode
    recs_off = [json.loads(l) for l in open(p_off["metrics"])]
    assert all("diag_bytes_to_host" not in r and "ess_forecast" not in r
               for r in recs_off)
    recs_on = [json.loads(l) for l in open(p_on["metrics"])]
    assert any("diag_bytes_to_host" in r for r in recs_on)


def test_escape_hatch_env_restores_fixed_march(tmp_path, monkeypatch):
    """STARK_STREAM_DIAG=0 STARK_ADAPTIVE_BLOCKS=0 == the explicit
    parameter opt-out: uniform block_size blocks, legacy metrics schema,
    bit-identical draws."""
    off, p_off = _run(tmp_path, "param", stream_diag=False,
                      adaptive_blocks=False, **_KW)
    monkeypatch.setenv("STARK_STREAM_DIAG", "0")
    monkeypatch.setenv("STARK_ADAPTIVE_BLOCKS", "0")
    env, p_env = _run(tmp_path, "env", **_KW)
    np.testing.assert_array_equal(off.draws_flat, env.draws_flat)
    steps = [r["draws_per_chain"] for r in env.history]
    assert steps == [20, 40, 60]  # uniform fixed march
    # identical metrics trail up to timing attribution
    strip = lambda rs: [  # noqa: E731
        {k: v for k, v in r.items()
         if k not in ("wall_s", "t_dispatch_s", "t_diag_s")}
        for r in rs
    ]
    assert strip(off.history) == strip(env.history)


def test_adaptive_budget_run_same_total_draws(tmp_path):
    """rhat_target=0 (budget-bounded): the adaptive scheduler draws
    exactly the fixed march's total — max_blocks*block_size per chain —
    only the block boundaries differ."""
    fixed, _ = _run(tmp_path, "fixed", adaptive_blocks=False, **_KW)
    adapt, _ = _run(tmp_path, "adapt", adaptive_blocks=True, **_KW)
    assert fixed.draws_flat.shape[1] == 60
    assert adapt.draws_flat.shape[1] == 60
    steps = [r["draws_per_chain"] for r in adapt.history]
    assert steps[-1] == 60 and steps[0] < 20  # geometric ramp start


def test_diag_bytes_constant_per_block(tmp_path):
    """With streaming on, the convergence gate's per-block host transfer
    is CONSTANT at O(chains*d*L) — independent of the accumulated draw
    count; the legacy gate's grows with the history."""
    p = tmp_path / "t.jsonl"
    chains, d, lags = 2, 2, STREAM_DIAG_LAGS
    with RunTrace(str(p)) as tr:
        stark_tpu.sample_until_converged(
            StdNormal2(), trace=tr, stream_diag=True, adaptive_blocks=False,
            **_KW,
        )
    events = read_trace(str(p))
    blocks = [e for e in events if e["event"] == "sample_block"]
    assert len(blocks) == 3
    sizes = [e["diag_bytes_to_host"] for e in blocks]
    # n:int32 + (anchor,s1,s2):(d,) + (cross,ring,head):(L,d), all f32
    expected = chains * 4 * (1 + 3 * d + 3 * lags * d)
    assert sizes == [expected] * 3, (sizes, expected)
    assert all(e["stream_diag"] is True for e in blocks)
    s = summarize_trace(events)
    assert s["diag"]["bytes_last"] == expected
    assert s["diag"]["bytes_max"] == expected
    assert s["diag"]["stream_diag"] is True

    # legacy gate: the transfer grows with the accumulated history
    p2 = tmp_path / "legacy.jsonl"
    with RunTrace(str(p2)) as tr:
        stark_tpu.sample_until_converged(
            StdNormal2(), trace=tr, stream_diag=False,
            adaptive_blocks=False, **_KW,
        )
    legacy = [e["diag_bytes_to_host"]
              for e in read_trace(str(p2)) if e["event"] == "sample_block"]
    assert legacy[0] < legacy[1] < legacy[2], legacy


@pytest.mark.slow  # >=8s on the 1-core host (pytest.ini policy, re-profiled 2026-08-03)
def test_adaptive_reduces_draws_eight_schools():
    """Acceptance: at equal targets on eight schools, the ESS-forecast
    scheduler converges in FEWER post-warmup draws than the fixed march
    (which can only stop on block_size boundaries), and both stops are
    full-pass validated."""
    from stark_tpu.models.eight_schools import EightSchools, eight_schools_data

    kw = dict(chains=4, block_size=400, min_blocks=1, max_blocks=4,
              rhat_target=1.05, ess_target=280.0, num_warmup=150,
              kernel="nuts", max_tree_depth=4, seed=0)
    fixed = stark_tpu.sample_until_converged(
        EightSchools(), eight_schools_data(), adaptive_blocks=False, **kw
    )
    adapt = stark_tpu.sample_until_converged(
        EightSchools(), eight_schools_data(), adaptive_blocks=True, **kw
    )
    assert fixed.converged and adapt.converged
    assert adapt.draws_flat.shape[1] < fixed.draws_flat.shape[1], (
        adapt.draws_flat.shape, fixed.draws_flat.shape
    )
    for post in (fixed, adapt):
        last = post.history[-1]
        assert last["full_min_ess"] > kw["ess_target"]
        assert last["full_max_rhat"] < kw["rhat_target"]
    # the overshoot estimate mirrors the draw saving
    assert adapt.overshoot_draws is not None
    assert fixed.overshoot_draws is not None
    assert adapt.overshoot_draws < fixed.overshoot_draws


def test_streaming_gate_never_stops_past_failed_validation():
    """Tier-1 guard: a (failpoint-forced) optimistic streaming gate makes
    the runner LOOK early, but the full-history validation pass still
    decides — with unreachable targets the run must never report
    convergence, and the rejected validations must be on record."""
    faults.reset()
    faults.configure("runner.gate.optimistic=nan*3")
    try:
        post = stark_tpu.sample_until_converged(
            StdNormal2(), chains=2, block_size=20, max_blocks=4,
            min_blocks=1, rhat_target=1.0001, ess_target=1e9,
            num_warmup=50, kernel="hmc", num_leapfrog=4, seed=0,
        )
    finally:
        faults.reset()
    assert not post.converged
    validated = [r for r in post.history if "full_min_ess" in r]
    assert validated, "forced-optimistic gate never reached validation"
    for r in validated:
        # every recorded validation REJECTED (ess target unreachable) —
        # and the run kept going: the last history record is not a stop
        assert r["full_min_ess"] < 1e9


def test_converged_stop_is_always_validated(tmp_path):
    """Every converged stop carries the full-pass record satisfying the
    targets — the streaming estimate alone can never stop a run."""
    post, _ = _run(
        tmp_path, "v", chains=4, block_size=50, max_blocks=8, min_blocks=1,
        rhat_target=1.2, ess_target=30.0, num_warmup=100, kernel="nuts",
        max_tree_depth=5, seed=0,
    )
    assert post.converged
    last = post.history[-1]
    assert last["full_min_ess"] > 30.0
    assert last["full_max_rhat"] < 1.2


def test_trace_report_renders_diag_table(tmp_path):
    """tools/trace_report.py surfaces the diagnostics-transfer table."""
    import importlib.util
    import io
    from contextlib import redirect_stdout

    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        stark_tpu.sample_until_converged(StdNormal2(), trace=tr, **_KW)
    spec = importlib.util.spec_from_file_location(
        "trace_report",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "trace_report.py"),
    )
    trace_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_report)
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert trace_report.main([str(p)]) == 0
    out = buf.getvalue()
    assert "diagnostics transfer" in out
    assert "gate transfer / block (last)" in out
    assert "streaming gate" in out
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert trace_report.main([str(p), "--json"]) == 0
    summary = json.loads(buf.getvalue())
    assert summary["diag"]["bytes_last"] > 0


def test_chees_stream_matches_plain_segment(tmp_path):
    """ChEES: the diag-carrying sample segment produces bit-identical
    draws to the plain one (the accumulator must not perturb the
    ensemble transitions)."""
    on, _ = _run(tmp_path, "on", chains=4, block_size=20, max_blocks=2,
                 min_blocks=2, rhat_target=0.0, num_warmup=40,
                 kernel="chees", map_init_steps=5, seed=1,
                 stream_diag=True, adaptive_blocks=False)
    off, _ = _run(tmp_path, "off", chains=4, block_size=20, max_blocks=2,
                  min_blocks=2, rhat_target=0.0, num_warmup=40,
                  kernel="chees", map_init_steps=5, seed=1,
                  stream_diag=False, adaptive_blocks=False)
    np.testing.assert_array_equal(on.draws_flat, off.draws_flat)


def test_resume_rebuilds_stream_state(tmp_path):
    """A resumed streaming run continues the accumulators from the stored
    draws: its post-resume gate sees the WHOLE history (min_ess keeps
    growing), and the adaptive ramp continues instead of restarting."""
    ckpt = str(tmp_path / "c.npz")
    p1 = stark_tpu.sample_until_converged(
        StdNormal2(), chains=2, block_size=50, max_blocks=2, min_blocks=2,
        rhat_target=0.5, num_warmup=100, kernel="hmc", num_leapfrog=8,
        seed=1, checkpoint_path=ckpt,
    )
    assert not p1.converged
    p2 = stark_tpu.sample_until_converged(
        StdNormal2(), block_size=50, max_blocks=4, min_blocks=2,
        rhat_target=0.5, num_warmup=100, kernel="hmc", num_leapfrog=8,
        resume_from=ckpt,
    )
    assert p2.num_samples == 200
    # the resumed run's first gate reading covers the resumed draws too
    first_resumed = p2.history[len(p1.history)]
    assert first_resumed["draws_per_chain"] > p1.history[-1]["draws_per_chain"]


@pytest.mark.slow
def test_sharded_backend_stream_and_adapt():
    """ShardedBackend: the chain-sharded diag carry runs under shard_map
    for both kernels; gate transfer stays O(chains*d*L)."""
    from stark_tpu.backends.sharded import ShardedBackend
    from stark_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": 2, "chains": 4})
    for kern, kw in (
        ("nuts", dict(max_tree_depth=4)),
        ("chees", dict(map_init_steps=5)),
    ):
        post = stark_tpu.sample_until_converged(
            StdNormal2(), backend=ShardedBackend(mesh=mesh), chains=4,
            block_size=30, max_blocks=3, min_blocks=3, rhat_target=0.0,
            num_warmup=40, kernel=kern, seed=0, **kw,
        )
        sizes = {r.get("diag_bytes_to_host") for r in post.history}
        assert len(sizes) == 1 and None not in sizes, sizes

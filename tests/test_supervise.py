"""Failure detection + supervised auto-restart (SURVEY.md §6)."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

import stark_tpu
from stark_tpu import supervise
from stark_tpu.checkpoint import save_checkpoint
from stark_tpu.model import Model, ParamSpec
from stark_tpu.supervise import (
    ChainHealthError,
    RestartBudget,
    agree_resume,
    backoff_delay,
    check_finite_state,
    checkpoint_health,
    checkpoint_is_healthy,
    classify_fault,
    supervised_sample,
)


class StdNormal2(Model):
    def param_spec(self):
        return {"x": ParamSpec((2,))}

    def log_prior(self, p):
        return -0.5 * jnp.sum(p["x"] ** 2)

    def log_lik(self, p, data):
        return jnp.zeros(())


SAMPLE_KW = dict(
    chains=2,
    block_size=50,
    max_blocks=20,
    rhat_target=1.05,
    ess_target=100.0,
    num_warmup=150,
    kernel="nuts",
    max_tree_depth=6,
)


def test_check_finite_state():
    good = {"z": np.zeros((2, 3)), "pe": np.ones(2), "step_size": np.ones(2)}
    check_finite_state(good)  # no raise
    bad = dict(good, step_size=np.array([0.1, np.nan]))
    with pytest.raises(ChainHealthError, match="step_size"):
        check_finite_state(bad)
    # the CARRIED grad seeds the next leapfrog half-step: must be finite
    with pytest.raises(ChainHealthError, match="grad"):
        check_finite_state(dict(good, grad=np.array([np.inf])))


def test_checkpoint_health(tmp_path):
    p = str(tmp_path / "c.npz")
    save_checkpoint(p, {"z": np.zeros((2, 2)), "pe": np.zeros(2)}, {})
    assert checkpoint_is_healthy(p)
    save_checkpoint(p, {"z": np.full((2, 2), np.nan), "pe": np.zeros(2)}, {})
    assert not checkpoint_is_healthy(p)
    with open(p, "wb") as f:
        f.write(b"not an npz")
    assert not checkpoint_is_healthy(p)
    assert not checkpoint_is_healthy(str(tmp_path / "missing.npz"))


@pytest.mark.slow
def test_supervised_clean_run(tmp_path):
    wd = str(tmp_path / "run")
    post = supervised_sample(StdNormal2(), workdir=wd, seed=0, **SAMPLE_KW)
    assert post.converged
    assert os.path.exists(os.path.join(wd, "chain.ckpt.npz"))
    assert os.path.exists(os.path.join(wd, "metrics.jsonl"))
    lines = [json.loads(l) for l in open(os.path.join(wd, "metrics.jsonl"))]
    assert not any(l["event"] == "restart" for l in lines)


@pytest.mark.slow
def test_supervised_restart_resumes_from_checkpoint(tmp_path, monkeypatch):
    """First attempt dies after checkpointing a block; the supervisor must
    resume from that checkpoint, and the restart must be JSONL-logged."""
    wd = str(tmp_path / "run")
    real = stark_tpu.runner.sample_until_converged
    calls = {"n": 0, "resumes": []}

    def flaky(model, data=None, **kw):
        calls["n"] += 1
        calls["resumes"].append(kw.get("resume_from"))
        if calls["n"] == 1:
            # run two blocks for real (so a checkpoint lands), then fault
            crashed = dict(kw, max_blocks=2, rhat_target=0.5)
            real(model, data, **crashed)
            raise RuntimeError("injected device fault")
        return real(model, data, **kw)

    monkeypatch.setattr(supervise, "sample_until_converged", flaky, raising=False)
    monkeypatch.setattr(
        stark_tpu.runner, "sample_until_converged", flaky
    )
    post = supervised_sample(
        StdNormal2(), workdir=wd, seed=0, max_restarts=2, **SAMPLE_KW
    )
    assert post.converged
    assert calls["n"] == 2
    assert calls["resumes"][0] is None
    assert calls["resumes"][1] is not None  # resumed from the checkpoint
    lines = [json.loads(l) for l in open(os.path.join(wd, "metrics.jsonl"))]
    restarts = [l for l in lines if l["event"] == "restart"]
    assert len(restarts) == 1
    assert "injected device fault" in restarts[0]["error"]
    assert restarts[0]["resumed_from_checkpoint"] is False


@pytest.mark.slow
def test_supervised_discards_poisoned_checkpoint(tmp_path):
    """A checkpoint with non-finite state is quarantined, not resumed."""
    wd = str(tmp_path / "run")
    os.makedirs(wd)
    ckpt = os.path.join(wd, "chain.ckpt.npz")
    save_checkpoint(
        ckpt,
        {
            "z": np.full((2, 2), np.nan),
            "pe": np.zeros(2),
            "step_size": np.ones(2),
            "inv_mass": np.ones((2, 2)),
            "key": np.zeros(2, np.uint32),
        },
        {"blocks_done": 3},
    )
    post = supervised_sample(StdNormal2(), workdir=wd, seed=0, **SAMPLE_KW)
    assert post.converged
    assert os.path.exists(ckpt + ".bad")  # quarantined, not silently reused
    # fresh run starts from block 0, so history has every block it ran
    assert post.history[0]["block"] == 1


@pytest.mark.slow
def test_reseed_branches_the_resumed_stream(tmp_path):
    """Resuming with reseed= must not replay the checkpointed key's draws —
    otherwise a deterministic failure repeats on every supervised retry."""
    ckpt = str(tmp_path / "state.npz")
    stark_tpu.sample_until_converged(
        StdNormal2(), chains=2, block_size=50, max_blocks=2, min_blocks=2,
        rhat_target=0.5, num_warmup=100, kernel="nuts", max_tree_depth=5,
        seed=0, checkpoint_path=ckpt,
    )
    common = dict(
        chains=2, block_size=50, max_blocks=3, min_blocks=3, rhat_target=0.5,
        num_warmup=100, kernel="nuts", max_tree_depth=5, resume_from=ckpt,
    )
    a = stark_tpu.sample_until_converged(StdNormal2(), **common)
    b = stark_tpu.sample_until_converged(StdNormal2(), **common, reseed=1)
    c = stark_tpu.sample_until_converged(StdNormal2(), **common)
    # same resume without reseed is deterministic; reseed diverges
    np.testing.assert_array_equal(a.draws_flat, c.draws_flat)
    assert not np.array_equal(a.draws_flat[:, 100:], b.draws_flat[:, 100:])


@pytest.mark.slow
def test_cold_start_quarantines_stale_draw_store(tmp_path):
    """Draws persisted by a discarded run must not leak into the new run."""
    from stark_tpu.drawstore import DrawStore, read_draws

    wd = str(tmp_path / "run")
    os.makedirs(wd)
    ckpt = os.path.join(wd, "chain.ckpt.npz")
    store = os.path.join(wd, "draws.stkr")
    # stale draws from a run whose checkpoint got poisoned
    ds = DrawStore(store, 2, 2)
    ds.append(np.full((2, 7, 2), 99.0, np.float32))
    ds.close()
    save_checkpoint(
        ckpt,
        {"z": np.full((2, 2), np.nan), "pe": np.zeros(2),
         "step_size": np.ones(2), "inv_mass": np.ones((2, 2)),
         "key": np.zeros(2, np.uint32)},
        {"blocks_done": 1},
    )
    post = supervised_sample(StdNormal2(), workdir=wd, seed=0, **SAMPLE_KW)
    assert post.converged
    assert os.path.exists(store + ".bad")
    stored, _, _ = read_draws(store, mmap=False)
    # store contains exactly this run's draws (no 7-draw stale block)
    assert stored.shape[0] == post.draws_flat.shape[1]
    assert not np.any(stored == 99.0)


@pytest.mark.slow
def test_resume_truncates_orphaned_store_rows(tmp_path):
    """Rows the async writer landed after the last completed checkpoint
    must be dropped on resume, or the re-run block double-counts."""
    from stark_tpu.drawstore import DrawStore, read_draws

    ckpt = str(tmp_path / "state.npz")
    store = str(tmp_path / "draws.stkr")
    post1 = stark_tpu.sample_until_converged(
        StdNormal2(), chains=2, block_size=50, max_blocks=2, min_blocks=2,
        rhat_target=0.5, num_warmup=100, kernel="nuts", max_tree_depth=5,
        seed=0, checkpoint_path=ckpt, draw_store_path=store,
    )
    # simulate the crash window: one extra block in the store, no checkpoint
    with DrawStore(store, 2, 2) as ds:
        ds.append(np.full((2, 50, 2), 7.7, np.float32))
    post2 = stark_tpu.sample_until_converged(
        StdNormal2(), chains=2, block_size=50, max_blocks=3, min_blocks=3,
        rhat_target=0.5, num_warmup=100, kernel="nuts", max_tree_depth=5,
        resume_from=ckpt, draw_store_path=store,
    )
    assert post2.draws_flat.shape[1] == 150  # 2 resumed + 1 new block
    assert not np.any(post2.draws_flat == 7.7)
    stored, _, _ = read_draws(store, mmap=False)
    assert not np.any(stored == 7.7)


def test_cyclic_empty_collect_raises():
    from stark_tpu.sghmc import sghmc_sample

    data = {"y": jnp.ones((64,))}
    with pytest.raises(ValueError, match="nothing would be collected"):
        sghmc_sample(
            StdNormal2(), data, batch_size=16, chains=1,
            num_warmup=10, num_samples=100, cycles=50, seed=0,
        )


def test_supervised_gives_up_after_max_restarts(tmp_path, monkeypatch):
    wd = str(tmp_path / "run")

    def always_fails(model, data=None, **kw):
        raise RuntimeError("permanent fault")

    monkeypatch.setattr(stark_tpu.runner, "sample_until_converged", always_fails)
    with pytest.raises(RuntimeError, match="permanent fault"):
        supervised_sample(
            StdNormal2(), workdir=wd, seed=0, max_restarts=2, **SAMPLE_KW
        )
    lines = [json.loads(l) for l in open(os.path.join(wd, "metrics.jsonl"))]
    assert sum(1 for l in lines if l["event"] == "restart") == 3


def test_classify_fault_taxonomy():
    from stark_tpu.faults import InjectedFault, InjectedPreemption
    from stark_tpu.watchdog import StallError

    assert classify_fault(ChainHealthError("nan")) == "poisoned_state"
    assert classify_fault(StallError("hung")) == "stall"
    assert classify_fault(RuntimeError("xla")) == "transient"
    assert classify_fault(InjectedFault("site")) == "transient"
    assert classify_fault(InjectedPreemption("site")) == "transient"


def test_checkpoint_health_reports_reason(tmp_path):
    p = str(tmp_path / "c.npz")
    save_checkpoint(p, {"z": np.zeros((2, 2)), "pe": np.zeros(2)}, {})
    assert checkpoint_health(p) == (True, None)
    save_checkpoint(p, {"z": np.full((2, 2), np.nan), "pe": np.zeros(2)}, {})
    ok, reason = checkpoint_health(p)
    assert not ok and reason.startswith("poisoned_state:") and "'z'" in reason
    with open(p, "wb") as f:
        f.write(b"garbage")
    ok, reason = checkpoint_health(p)
    assert not ok and reason.startswith("corrupt_checkpoint:")


def test_restart_budget_lifetime_and_window():
    # window=None: the historical lifetime counter
    b = RestartBudget(2)
    for t in (0.0, 1.0):
        b.record_failure(t)
        assert not b.exhausted(t)
    b.record_failure(2.0)
    assert b.exhausted(2.0)
    # sliding window: three failures in 10s trip a max of 2 ...
    w = RestartBudget(2, window_s=10.0)
    for t in (0.0, 1.0, 2.0):
        w.record_failure(t)
    assert w.exhausted(2.0)
    # ... but the same three spread over hours never do (rate, not count)
    w2 = RestartBudget(2, window_s=10.0)
    for t in (0.0, 3600.0, 7200.0):
        w2.record_failure(t)
        assert not w2.exhausted(t)


def test_restart_budget_window_boundary():
    """The window edge is INCLUSIVE: a failure aged exactly
    ``window_s`` seconds still counts; one tick past it ages out.  The
    pruning is applied at query time, so the same budget object answers
    both sides of the edge correctly."""
    w = RestartBudget(1, window_s=10.0)
    w.record_failure(0.0)
    w.record_failure(10.0)  # exactly at the edge of failure #1's window
    assert w.in_window(10.0) == 2
    assert w.exhausted(10.0)
    # one tick later the first failure leaves the window: back in budget
    assert w.in_window(10.0 + 1e-6) == 1
    assert not w.exhausted(10.0 + 1e-6)
    # and the pruning is permanent — re-asking at the edge time cannot
    # resurrect the aged-out failure
    assert w.in_window(10.0) == 1

    # window_s=None NEVER forgets, however far apart the failures land
    inf = RestartBudget(1, window_s=None)
    inf.record_failure(0.0)
    assert not inf.exhausted(1e9)
    inf.record_failure(1e9)
    assert inf.exhausted(1e9)
    assert inf.exhausted(1e12)  # still exhausted eons later

    # max_restarts=0: the FIRST failure is terminal in any window
    zero = RestartBudget(0, window_s=10.0)
    zero.record_failure(5.0)
    assert zero.exhausted(5.0)


def test_backoff_delay_policy():
    # base 0 (the default) keeps restarts immediate
    assert backoff_delay("transient", 1, base_s=0.0) == 0.0
    # poisoned state restarts immediately regardless of base
    assert backoff_delay("poisoned_state", 3, base_s=5.0) == 0.0
    # exponential growth with deterministic jitter in [0.5, 1.5)
    d1 = backoff_delay("transient", 1, base_s=1.0, seed=7)
    d2 = backoff_delay("transient", 2, base_s=1.0, seed=7)
    assert d1 == backoff_delay("transient", 1, base_s=1.0, seed=7)
    assert 0.5 <= d1 < 1.5 and 1.0 <= d2 < 3.0
    # the cap bounds the DELIVERED delay, jitter included
    for a in range(1, 40):
        assert backoff_delay("transient", a, base_s=1.0, cap_s=4.0, seed=a) <= 4.0


def test_supervised_restart_window_bounds_rate(tmp_path, monkeypatch):
    """Fast repeated failures overflow the window and raise; the restart
    records carry the fault class and backoff."""
    wd = str(tmp_path / "run")

    def always_fails(model, data=None, **kw):
        raise RuntimeError("crash loop")

    monkeypatch.setattr(stark_tpu.runner, "sample_until_converged", always_fails)
    with pytest.raises(RuntimeError, match="crash loop"):
        supervised_sample(
            StdNormal2(), workdir=wd, seed=0, max_restarts=1,
            restart_window_s=3600.0, backoff_base_s=0.01, **SAMPLE_KW
        )
    lines = [json.loads(l) for l in open(os.path.join(wd, "metrics.jsonl"))]
    rs = [l for l in lines if l["event"] == "restart"]
    assert len(rs) == 2  # failure 2 overflows max_restarts=1 in-window
    assert all(r["fault"] == "transient" for r in rs)
    assert rs[0]["backoff_s"] > 0  # jittered exponential before retry
    assert rs[-1]["backoff_s"] == 0  # no pointless sleep before giving up


def test_supervised_quarantine_reason_logged_and_traced(tmp_path, monkeypatch):
    """A discarded checkpoint must say WHY — in the log and as a
    chain_health quarantine trace event — never silently."""
    from stark_tpu.telemetry import RunTrace, read_trace

    wd = str(tmp_path / "run")
    os.makedirs(wd)
    ckpt = os.path.join(wd, "chain.ckpt.npz")
    save_checkpoint(
        ckpt, {"z": np.full((2, 2), np.nan), "pe": np.zeros(2)}, {}
    )
    monkeypatch.setattr(
        stark_tpu.runner, "sample_until_converged",
        lambda model, data=None, **kw: "sentinel",
    )
    tpath = str(tmp_path / "trace.jsonl")
    with RunTrace(tpath) as trace:
        out = supervised_sample(
            StdNormal2(), workdir=wd, seed=0, trace=trace, **SAMPLE_KW
        )
    assert out == "sentinel"
    assert os.path.exists(ckpt + ".bad")
    quar = [
        e for e in read_trace(tpath)
        if e["event"] == "chain_health" and e.get("status") == "quarantine"
    ]
    assert len(quar) == 1 and quar[0]["reason"].startswith("poisoned_state:")


class _FakeAllgather:
    """Stand-in for multihost_utils.process_allgather: stacks this rank's
    report with a scripted peer report."""

    def __init__(self, peer):
        self.peer = peer
        self.saw = None

    def __call__(self, x, tiled=False):
        assert not tiled, "agree_resume gathers rank-stacked reports"
        self.saw = tuple(int(v) for v in np.asarray(x))
        return np.stack([np.asarray(x), np.asarray(self.peer)])


def _fake_multiprocess(monkeypatch, peer):
    import jax
    from jax.experimental import multihost_utils

    fake = _FakeAllgather(peer)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "process_allgather", fake)
    return fake


def test_agree_resume_single_process_passthrough(tmp_path):
    p = str(tmp_path / "c.npz")
    assert agree_resume(p, quarantine=lambda _: 1 / 0) == p
    assert agree_resume(None, quarantine=lambda _: 1 / 0) is None


def test_agree_resume_all_ranks_agree(tmp_path, monkeypatch):
    ckpt = str(tmp_path / "c.npz")
    save_checkpoint(ckpt, {"z": np.zeros(2)}, {"blocks_done": 3})
    fake = _fake_multiprocess(monkeypatch, peer=(1, 3))
    quarantined = []
    assert agree_resume(ckpt, quarantine=quarantined.append) == ckpt
    assert fake.saw == (1, 3)  # sample phase, 3 blocks
    assert quarantined == []


def test_agree_resume_skew_quarantines(tmp_path, monkeypatch):
    """A one-block skew (peer checkpointed block 2, we hold block 3) must
    cold-start BOTH ranks and quarantine our healthy-but-unusable file."""
    ckpt = str(tmp_path / "c.npz")
    save_checkpoint(ckpt, {"z": np.zeros(2)}, {"blocks_done": 3})
    _fake_multiprocess(monkeypatch, peer=(1, 2))
    quarantined = []
    assert agree_resume(ckpt, quarantine=quarantined.append) is None
    assert quarantined == [ckpt]


def test_agree_resume_peer_cold_quarantines(tmp_path, monkeypatch):
    ckpt = str(tmp_path / "c.npz")
    save_checkpoint(ckpt, {"z": np.zeros(2)}, {"blocks_done": 1})
    _fake_multiprocess(monkeypatch, peer=(-1, -1))
    quarantined = []
    assert agree_resume(ckpt, quarantine=quarantined.append) is None
    assert quarantined == [ckpt]


def test_agree_resume_self_cold_no_quarantine(monkeypatch):
    """A rank with nothing to resume reports cold and cold-starts without
    quarantining anything (there is no file to protect)."""
    fake = _fake_multiprocess(monkeypatch, peer=(1, 2))
    quarantined = []
    assert agree_resume(None, quarantine=quarantined.append) is None
    assert fake.saw == (-1, -1)
    assert quarantined == []


def test_agree_resume_warmup_phase_distinct(tmp_path, monkeypatch):
    """A warmup-2 checkpoint must never falsely agree with a blocks-2 one:
    the phase rides in the report."""
    ckpt = str(tmp_path / "c.npz")
    save_checkpoint(
        ckpt, {"z": np.zeros(2)}, {"phase": "warmup", "warm_done": 2}
    )
    fake = _fake_multiprocess(monkeypatch, peer=(1, 2))
    quarantined = []
    assert agree_resume(ckpt, quarantine=quarantined.append) is None
    assert fake.saw == (0, 2)  # warmup phase tag
    assert quarantined == [ckpt]


def test_ranks_agree_rule():
    """Multi-process resume consistency (VERDICT r4 #3 follow-up): resume
    only when every rank holds a healthy checkpoint at the same
    (phase, progress); any cold, unreadable, or skewed rank cold-starts
    the whole pod in lockstep."""
    from stark_tpu.supervise import _ranks_agree

    assert _ranks_agree([(1, 3), (1, 3)])          # same sample-phase block
    assert _ranks_agree([(0, 2), (0, 2)])          # same warmup segment
    assert not _ranks_agree([(1, 3), (1, 2)])      # one-block skew
    assert not _ranks_agree([(0, 2), (1, 2)])      # warmup vs sample phase
    assert not _ranks_agree([(-1, -1), (1, 3)])    # one rank cold
    assert not _ranks_agree([(-1, -1), (-1, -1)])  # all cold
    assert _ranks_agree([(1, 5)])                  # degenerate single rank


def test_rank_path_single_process_identity_and_idempotence(monkeypatch):
    """rank_path: identity single-process; per-rank suffix inserted once
    (supervisor and runner both apply it) on multi-process runs."""
    import jax

    from stark_tpu.checkpoint import rank_path

    assert rank_path(None) is None
    assert rank_path("a/b.npz") == "a/b.npz"  # process_count() == 1

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    p = rank_path("a/b.npz")
    assert p == "a/b.p1.npz"
    assert rank_path(p) == p  # idempotent
    assert rank_path("noext") == "noext.p1"
    assert rank_path(None) is None

"""Telemetry layer: schema round-trip, NullTrace no-op, traced runs.

The trace is a durable artifact other tooling parses (trace_report,
bench.py), so the contract under test is the SCHEMA: envelope fields on
every event, version rejection on mismatch, run ordinals, phase durations
that tile the run wall, and the canonical run_start -> sample_block ->
run_end ordering on a real eight_schools run.
"""

import io
import json
import os
import time
from contextlib import redirect_stdout

import jax.numpy as jnp
import numpy as np
import pytest

import stark_tpu
from stark_tpu import telemetry
from stark_tpu.model import Model, ParamSpec
from stark_tpu.telemetry import (
    EVENT_TYPES,
    NULL_TRACE,
    SCHEMA_VERSION,
    NullTrace,
    RunTrace,
    TraceError,
    read_trace,
    summarize_trace,
    use_trace,
    validate_event,
)


class StdNormal2(Model):
    def param_spec(self):
        return {"x": ParamSpec((2,))}

    def log_prior(self, p):
        return -0.5 * jnp.sum(p["x"] ** 2)

    def log_lik(self, p, data):
        return jnp.zeros(())


# ---------------------------------------------------------------------------
# schema round-trip
# ---------------------------------------------------------------------------


def test_emit_jsonl_roundtrip(tmp_path):
    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        tr.emit("run_start", model="M", kernel="nuts", chains=4)
        tr.emit("chain_health", mean_accept=0.8, num_divergent=3)
        tr.emit("run_end", dur_s=1.25)
    events = read_trace(str(p))
    assert [e["event"] for e in events] == [
        "run_start", "chain_health", "run_end"
    ]
    for e in events:
        assert e["schema"] == SCHEMA_VERSION
        assert e["run"] == 1
        assert isinstance(e["ts"], float) and isinstance(e["wall_s"], float)
    assert events[0]["model"] == "M" and events[0]["chains"] == 4
    assert events[1]["mean_accept"] == 0.8
    assert events[2]["dur_s"] == 1.25
    # every canonical event type is representable and survives round-trip
    assert {"run_start", "chain_health", "run_end"} <= EVENT_TYPES


def test_run_ordinals_and_tags(tmp_path):
    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        tr.emit("run_start")
        tr.emit("run_end", dur_s=0.1)
        shard = tr.tagged(shard=3, component="consensus")
        shard.emit("run_start")
        shard.emit("chain_health", step_size=0.5)
    events = read_trace(str(p))
    assert [e["run"] for e in events] == [1, 1, 2, 2]
    assert events[3]["shard"] == 3 and events[3]["component"] == "consensus"
    # tagged views share the file and run counter; tags never leak back
    assert "shard" not in events[0]


def test_validate_event_rejects_bad_envelope():
    good = {"schema": SCHEMA_VERSION, "event": "run_start", "ts": 1.0,
            "wall_s": 0.0, "run": 1}
    assert validate_event(dict(good)) == good
    with pytest.raises(TraceError):
        validate_event({k: v for k, v in good.items() if k != "ts"})
    with pytest.raises(TraceError):
        validate_event({**good, "schema": SCHEMA_VERSION + 1})
    # unknown event TYPES are forward-compatible, never an error
    validate_event({**good, "event": "a_future_event"})


def test_read_trace_strict_and_lenient(tmp_path):
    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        tr.emit("run_start")
    with open(p, "a") as f:
        f.write('{"torn line...')  # live file killed mid-write
    with pytest.raises(TraceError):
        read_trace(str(p))
    events = read_trace(str(p), strict=False)
    assert len(events) == 1 and events[0]["event"] == "run_start"


def test_phase_emits_duration_and_error_class(tmp_path):
    p = tmp_path / "t.jsonl"
    tr = RunTrace(str(p))
    with tr.phase("sample_block", block=1) as ph:
        time.sleep(0.01)
        ph.note(mean_accept=0.9)
    with pytest.raises(RuntimeError):
        with tr.phase("warmup_block"):
            raise RuntimeError("fault mid-phase")
    tr.close()
    blk, warm = read_trace(str(p))
    assert blk["event"] == "sample_block" and blk["dur_s"] >= 0.01
    assert blk["block"] == 1 and blk["mean_accept"] == 0.9
    # the failed phase still records its timing + the fault class: that is
    # the stalled-run evidence the layer exists for
    assert warm["event"] == "warmup_block" and warm["error"] == "RuntimeError"
    assert warm["dur_s"] >= 0.0


def test_heartbeat_is_rate_limited(tmp_path):
    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        for i in range(50):
            tr.heartbeat(min_interval_s=10.0, label="sample", step=i)
    events = read_trace(str(p))
    assert len(events) == 1  # 49 of 50 dropped by the limiter
    assert events[0]["event"] == "progress" and events[0]["step"] == 0


def test_emit_survives_closed_file(tmp_path):
    tr = RunTrace(str(tmp_path / "t.jsonl"))
    tr.emit("run_start")
    tr.close()
    # observability must never kill the run: emits after close are dropped
    assert tr.emit("run_end") is None
    with tr.phase("sample_block"):
        pass


# ---------------------------------------------------------------------------
# NullTrace: the no-op default
# ---------------------------------------------------------------------------


def test_nulltrace_is_default_and_noop(tmp_path):
    assert isinstance(telemetry.get_trace(), NullTrace)
    assert not NULL_TRACE.enabled
    assert NULL_TRACE.emit("run_start", anything=1) is None
    assert NULL_TRACE.tagged(shard=0) is NULL_TRACE
    ph = NULL_TRACE.phase("sample_block")
    with ph as inner:
        assert inner.note(x=1) is inner
    NULL_TRACE.heartbeat(label="x", step=0)
    NULL_TRACE.close()
    # the shared no-op phase is a singleton: no per-block allocation
    assert NULL_TRACE.phase("a") is NULL_TRACE.phase("b")


def test_use_trace_scopes_and_restores(tmp_path):
    tr = RunTrace(str(tmp_path / "t.jsonl"))
    assert telemetry.get_trace() is NULL_TRACE
    with use_trace(tr) as got:
        assert got is tr and telemetry.get_trace() is tr
        with use_trace(None):
            assert telemetry.get_trace() is NULL_TRACE
        assert telemetry.get_trace() is tr
    assert telemetry.get_trace() is NULL_TRACE
    tr.close()


def test_nulltrace_runs_pay_nothing(tmp_path):
    """An untraced run must not write anywhere or change results: same
    seeds with and without an (enabled) trace give identical draws."""
    post_plain = stark_tpu.sample(
        StdNormal2(), chains=2, kernel="hmc", num_leapfrog=4,
        num_warmup=20, num_samples=20, seed=0,
    )
    p = tmp_path / "t.jsonl"
    with use_trace(RunTrace(str(p))) as tr:
        post_traced = stark_tpu.sample(
            StdNormal2(), chains=2, kernel="hmc", num_leapfrog=4,
            num_warmup=20, num_samples=20, seed=0,
        )
        tr.close()
    np.testing.assert_array_equal(post_plain.draws_flat, post_traced.draws_flat)
    assert len(read_trace(str(p))) >= 3  # and the traced run DID record


# ---------------------------------------------------------------------------
# traced runs: the canonical event stream
# ---------------------------------------------------------------------------


def _run_eight_schools(trace):
    from stark_tpu.backends import JaxBackend
    from stark_tpu.models import EightSchools, eight_schools_data

    backend = JaxBackend()  # shared so the traced pass hits the jit cache
    kwargs = dict(
        chains=2, kernel="nuts", max_tree_depth=5, num_warmup=50,
        num_samples=50, seed=0, backend=backend,
    )
    with use_trace(NULL_TRACE):
        stark_tpu.sample(EightSchools(), eight_schools_data(), **kwargs)
    with use_trace(trace):
        stark_tpu.sample(EightSchools(), eight_schools_data(), **kwargs)


@pytest.mark.slow  # >=8s on the 1-core host (pytest.ini policy, re-profiled 2026-08-03)
def test_eight_schools_trace_smoke(tmp_path):
    """The acceptance-shaped smoke: an eight_schools run under a trace
    produces run_start -> sample_block -> run_end IN ORDER, carries
    acceptance + divergence counts, and its phase durations tile the
    run wall (compile-cached pass, same contract as --trace on the CLI
    bench path)."""
    p = tmp_path / "t.jsonl"
    tr = RunTrace(str(p))
    _run_eight_schools(tr)
    tr.close()
    events = read_trace(str(p))
    names = [e["event"] for e in events]
    # ordered core: run_start before sample_block before run_end
    assert names.index("run_start") < names.index("sample_block") < names.index("run_end")
    health = [e for e in events if e["event"] == "chain_health"]
    assert health and "mean_accept" in health[-1]
    assert "num_divergent" in health[-1]

    s = summarize_trace(events)
    assert s["meta"]["model"] == "EightSchools"
    phase_sum = sum(v["total_s"] for v in s["phases"].values())
    assert s["wall_s"] > 0
    # summed phase durations within 10% of the run wall (the compile-
    # cached pass — cold passes hide XLA compile outside any dispatch)
    assert abs(phase_sum - s["wall_s"]) / s["wall_s"] < 0.10


def test_adaptive_runner_trace_events(tmp_path):
    """sample_until_converged emits the full vocabulary: compile,
    warmup_block(s), per-block sample_block + chain_health (R-hat/ESS/
    step size), checkpoint timings, run_end."""
    p = tmp_path / "t.jsonl"
    ckpt = tmp_path / "c.npz"
    tr = RunTrace(str(p))
    post = stark_tpu.sample_until_converged(
        StdNormal2(), chains=2, block_size=20, max_blocks=3, min_blocks=1,
        rhat_target=1.5, ess_target=5.0, num_warmup=60, kernel="nuts",
        max_tree_depth=4, seed=0, checkpoint_path=str(ckpt), trace=tr,
    )
    tr.close()
    events = read_trace(str(p))
    names = [e["event"] for e in events]
    assert names[0] == "run_start" and names[-1] == "run_end"
    for required in ("compile", "warmup_block", "sample_block",
                     "chain_health", "checkpoint"):
        assert required in names, f"missing {required}: {names}"
    # block-level health carries the live convergence signal
    block_health = [e for e in events
                    if e["event"] == "chain_health" and "max_rhat" in e]
    assert block_health
    h = block_health[-1]
    assert h["min_ess"] > 0 and h["step_size"] > 0
    assert h["num_divergent"] >= 0 and "mean_accept" in h
    end = events[-1]
    assert end["converged"] == post.converged
    assert end["blocks"] == len(post.history)


@pytest.mark.slow  # >=8s on the 1-core host (pytest.ini policy, re-profiled 2026-08-03)
def test_trace_report_renders_phase_and_health_table(tmp_path):
    """tools/trace_report.py renders a per-phase table including
    acceptance rate and divergence counts from a real trace."""
    import importlib.util

    p = tmp_path / "t.jsonl"
    tr = RunTrace(str(p))
    _run_eight_schools(tr)
    tr.close()

    spec = importlib.util.spec_from_file_location(
        "trace_report",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "trace_report.py"),
    )
    trace_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_report)

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = trace_report.main([str(p)])
    out = buf.getvalue()
    assert rc == 0
    assert "phase" in out and "sample_block" in out
    assert "acceptance rate" in out and "divergences" in out

    # --json mode emits the machine-readable summary
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = trace_report.main([str(p), "--json"])
    assert rc == 0
    summary = json.loads(buf.getvalue())
    assert summary["phases"] and "mean_accept" in summary["health"]


def test_in_loop_heartbeat_progress_events(tmp_path):
    """progress_every wires a jit-safe jax.debug.callback heartbeat into
    the compiled sampling scan; events land in the trace from the
    callback thread."""
    p = tmp_path / "t.jsonl"
    with use_trace(RunTrace(str(p))) as tr:
        stark_tpu.sample(
            StdNormal2(), chains=2, kernel="hmc", num_leapfrog=4,
            num_warmup=10, num_samples=60, seed=0, progress_every=25,
        )
        import jax

        jax.effects_barrier()
        tr.close()
    events = read_trace(str(p), strict=False)
    progress = [e for e in events if e["event"] == "progress"]
    assert progress, "no progress heartbeat reached the trace"
    assert progress[0]["label"] == "sample"
    assert 0.0 <= progress[0]["accept"] <= 1.0


def test_summarize_trace_counts_restarts(tmp_path):
    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        tr.emit("run_start")
        tr.emit("chain_health", status="restart", attempt=1,
                error="ChainHealthError: boom")
        tr.emit("chain_health", status="restart", attempt=2,
                error="XlaRuntimeError: tunnel")
        tr.emit("run_end", dur_s=2.0)
    s = summarize_trace(read_trace(str(p)))
    assert s["restarts"] == 2


def test_restarts_counted_across_runs(tmp_path):
    """The supervisor stamps a restart with the FAILED attempt's run
    ordinal; the summary of the (later, successful) run must still count
    it — restart totals are a whole-trace property."""
    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        tr.emit("run_start")  # attempt 1 (faults)
        tr.emit("chain_health", status="restart", attempt=1,
                error="ChainHealthError: boom")
        tr.emit("run_start")  # attempt 2 (succeeds)
        tr.emit("run_end", dur_s=1.0)
    s = summarize_trace(read_trace(str(p)))
    assert s["run"] == 2 and s["restarts"] == 1


def test_restarts_not_absorbed_from_earlier_sessions(tmp_path):
    """A clean run appended after an earlier session's restarts must not
    inherit them: the chain-walk stops at a predecessor run with no
    restart event (the earlier session's successful final run)."""
    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:  # session 1: one restart, then success
        tr.emit("run_start")
        tr.emit("chain_health", status="restart", attempt=1, error="boom")
        tr.emit("run_start")
        tr.emit("run_end", dur_s=1.0)
    with RunTrace(str(p)) as tr:  # session 2: clean
        tr.emit("run_start")
        tr.emit("run_end", dur_s=2.0)
    events = read_trace(str(p))
    assert summarize_trace(events)["restarts"] == 0  # run 3, clean story
    assert summarize_trace(events, run=2)["restarts"] == 1


def test_chees_progress_heartbeat(tmp_path):
    """progress_every reaches the ChEES ensemble sampling scan too (the
    flagship path)."""
    from stark_tpu.models import Logistic, synth_logistic_data
    import jax

    data, _ = synth_logistic_data(jax.random.PRNGKey(0), 200, 3)
    p = tmp_path / "t.jsonl"
    with use_trace(RunTrace(str(p))) as tr:
        stark_tpu.sample(
            Logistic(num_features=3), data, chains=4, kernel="chees",
            num_warmup=20, num_samples=60, init_step_size=0.1,
            progress_every=25, seed=0,
        )
        jax.effects_barrier()
        tr.close()
    progress = [e for e in read_trace(str(p), strict=False)
                if e["event"] == "progress"]
    assert progress and progress[0]["label"] == "chees_sample"


def test_reopened_trace_continues_run_ordinals(tmp_path):
    """Appending a second session to the same --trace PATH must continue
    the run numbering, never collide with the first session's runs."""
    p = tmp_path / "t.jsonl"
    with RunTrace(str(p)) as tr:
        tr.emit("run_start")
        tr.emit("run_end", dur_s=0.5)
    with RunTrace(str(p)) as tr:  # new process/session, same file
        tr.emit("run_start")
        tr.emit("run_end", dur_s=0.7)
    events = read_trace(str(p))
    assert [e["run"] for e in events] == [1, 1, 2, 2]
    assert summarize_trace(events)["wall_s"] == 0.7  # last run, unmerged


# ---------------------------------------------------------------------------
# event listeners + in-memory bus (the live-exporter fan-out)
# ---------------------------------------------------------------------------


def test_event_listeners_receive_every_record(tmp_path):
    p = tmp_path / "t.jsonl"
    seen = []
    telemetry.add_event_listener(seen.append)
    try:
        with RunTrace(str(p)) as tr:
            tr.emit("run_start", model="M")
            with tr.phase("sample_block", block=1):
                pass
    finally:
        telemetry.remove_event_listener(seen.append)
    assert [e["event"] for e in seen] == ["run_start", "sample_block"]
    # listeners see the SAME record that lands in the file
    events = read_trace(str(p))
    assert seen[0] == events[0] and seen[1] == events[1]
    # removed: no further delivery
    with RunTrace(str(p)) as tr:
        tr.emit("run_end", dur_s=0.1)
    assert len(seen) == 2


def test_in_memory_trace_feeds_listeners_writes_nothing(tmp_path):
    seen = []
    telemetry.add_event_listener(seen.append)
    try:
        tr = RunTrace(None)  # the status daemon's untraced mode
        assert tr.path is None and tr.enabled
        tr.emit("run_start", model="M")
        tr.emit("run_end", dur_s=0.2)
    finally:
        telemetry.remove_event_listener(seen.append)
    assert [e["event"] for e in seen] == ["run_start", "run_end"]
    assert seen[0]["run"] == 1 and seen[0]["schema"] == SCHEMA_VERSION
    assert list(tmp_path.iterdir()) == []  # nothing hit the filesystem


def test_in_memory_trace_without_listeners_is_noop():
    tr = RunTrace(None)
    assert tr.emit("run_start") is None  # nothing to deliver to


def test_listener_exception_never_reaches_the_run(tmp_path):
    p = tmp_path / "t.jsonl"

    def bad(rec):
        raise RuntimeError("listener bug")

    telemetry.add_event_listener(bad)
    try:
        with RunTrace(str(p)) as tr:
            assert tr.emit("run_start") is not None
    finally:
        telemetry.remove_event_listener(bad)
    assert read_trace(str(p))[0]["event"] == "run_start"


def test_no_listener_no_record_overhead(tmp_path):
    """The zero-cost contract: without listeners, an emit on a file-less
    trace builds nothing, and NullTrace still does nothing at all."""
    assert not telemetry._EVENT_LISTENERS
    assert RunTrace(None).emit("sample_block") is None
    assert NULL_TRACE.emit("sample_block") is None


# ---------------------------------------------------------------------------
# provenance stamping (satellite: attributable ledger rows / run_starts)
# ---------------------------------------------------------------------------


def test_provenance_fields_and_caching():
    prov = telemetry.provenance()
    assert set(prov) == {"git_sha", "jax_version", "jaxlib_version"}
    # best-effort: values may be None, but in this repo git + jax exist
    assert prov["jax_version"]
    assert prov["git_sha"]
    # cached: the second call is the same content, not a new subprocess
    assert telemetry.provenance() == prov
    # callers mutate their copy safely
    prov["git_sha"] = "clobbered"
    assert telemetry.provenance()["git_sha"] != "clobbered"


def test_run_start_carries_provenance_and_device_kind(tmp_path):
    p = tmp_path / "t.jsonl"
    with use_trace(RunTrace(str(p))):
        stark_tpu.sample(
            StdNormal2(), chains=2, kernel="hmc", num_leapfrog=4,
            num_warmup=5, num_samples=5, seed=0,
        )
    start = read_trace(str(p), strict=False)[0]
    assert start["event"] == "run_start"
    for k in ("git_sha", "jax_version", "jaxlib_version", "device_kind"):
        assert k in start, k
    # summarize_trace surfaces them through meta (the ledger reads this)
    meta = summarize_trace(read_trace(str(p), strict=False))["meta"]
    assert "git_sha" in meta and "jax_version" in meta


# ---------------------------------------------------------------------------
# PR-1-era traces degrade gracefully in the report tool (satellite)
# ---------------------------------------------------------------------------


def _pr1_era_trace(path):
    """A trace as PR 1 wrote them: no overlap/diag/block_len/provenance
    fields anywhere."""
    events = [
        {"event": "run_start", "entry": "sample", "model": "M",
         "kernel": "nuts", "chains": 4, "platform": "cpu",
         "device_count": 1},
        {"event": "compile", "dur_s": 0.5, "stage": "setup"},
        {"event": "warmup_block", "dur_s": 0.3, "start": 0, "end": 50},
        {"event": "sample_block", "dur_s": 0.4, "t_dispatch_s": 0.3,
         "t_diag_s": 0.1},
        {"event": "chain_health", "max_rhat": 1.01, "min_ess": 200.0,
         "mean_accept": 0.8, "num_divergent": 0},
        {"event": "checkpoint", "dur_s": 0.05},
        {"event": "run_end", "dur_s": 1.0, "num_divergent": 0},
    ]
    with open(path, "w") as f:
        for i, e in enumerate(events):
            f.write(json.dumps({
                "schema": SCHEMA_VERSION, "ts": 1.0 + i,
                "wall_s": float(i), "run": 1, **e,
            }) + "\n")


def test_trace_report_degrades_on_pr1_era_traces(tmp_path):
    """Traces that predate the overlap/diag fields must render (no
    KeyError), simply omitting the newer tables; --json emits the
    summarize_trace dict with empty overlap/diag sections."""
    import importlib.util

    p = tmp_path / "old.jsonl"
    _pr1_era_trace(p)

    spec = importlib.util.spec_from_file_location(
        "trace_report",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "trace_report.py"),
    )
    trace_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_report)

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert trace_report.main([str(p)]) == 0
    out = buf.getvalue()
    assert "sample_block" in out and "max R-hat" in out
    assert "block overlap" not in out  # absent, not crashed

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert trace_report.main([str(p), "--json"]) == 0
    summary = json.loads(buf.getvalue())
    assert summary["overlap"] == {} and summary["diag"] == {}
    assert summary["health"]["max_rhat"] == 1.01
    # the ledger ingests the same dict without choking on the gaps
    from stark_tpu import ledger

    row = ledger.make_row(source="test", config="old", trace_summary=summary)
    assert row["device_idle_frac"] is None
    assert row["ess_per_sec"] == pytest.approx(200.0)


def test_trace_report_renders_na_for_missing_values():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "trace_report",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "trace_report.py"),
    )
    trace_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_report)
    assert trace_report._fmt(None) == "n/a"


# ---------------------------------------------------------------------------
# PR 15 satellite bugfix: health.num_divergent is cumulative-with-reset
# across supervised attempts, not the latest event's value
# ---------------------------------------------------------------------------


def _attempt_events(run, divs, restart_after=False, resumed=False):
    """One supervised attempt's skeleton: run_start (stamped
    ``resuming`` exactly as the runner does — bool(resume_from)), a
    per-block chain_health divergence trail, optionally the failed
    attempt's restart record (stamped with THIS run's ordinal, as
    supervise does)."""
    evs = [{"event": "run_start", "model": "M", "kernel": "nuts",
            "resuming": bool(resumed)}]
    if resumed:
        # a checkpoint-resumed attempt re-emits warmup_done without a
        # fresh warmup; its block counters CONTINUE the restored total
        evs.append({"event": "chain_health", "status": "warmup_done",
                    "num_divergent": 7})
    for d in divs:
        evs.append({"event": "chain_health", "mean_accept": 0.8,
                    "num_divergent": d})
    if restart_after:
        evs.append({"event": "chain_health", "status": "restart",
                    "fault": "transient", "attempt": run})
    else:
        evs.append({"event": "run_end", "dur_s": 1.0})
    return [
        {"schema": SCHEMA_VERSION, "ts": 0.0, "wall_s": 0.0, "run": run,
         **e}
        for e in evs
    ]


def test_summarize_num_divergent_accumulates_across_cold_restarts():
    """A cold retry restarts its cumulative counter from zero: the
    failed attempt's final count must be banked, not discarded (the
    old latest-event semantics reported 2 here) — including when the
    retry happens to reach a HIGHER count than the failed attempt (no
    value decrease is ever observed; the run_start boundary is the
    reset signal, not the values)."""
    events = (
        _attempt_events(1, [2, 3], restart_after=True)
        + _attempt_events(2, [1, 2])
    )
    s = summarize_trace(events)
    assert s["run"] == 2 and s["restarts"] == 1
    assert s["health"]["num_divergent"] == 5  # 3 banked + 2 current
    # monotone-looking cold retry: attempt 1 ends at 5, attempt 2
    # reaches 7 with no observed decrease — still 5 + 7
    events = (
        _attempt_events(1, [5], restart_after=True)
        + _attempt_events(2, [6, 7])
    )
    assert summarize_trace(events)["health"]["num_divergent"] == 12


def test_summarize_num_divergent_resumed_attempt_not_double_counted():
    """A checkpoint-resumed retry CONTINUES the restored counter (no
    decrease) — cumulative-with-reset must not double count it, and the
    warmup_done record's warmup divergences stay out of the number."""
    events = (
        _attempt_events(1, [2, 3], restart_after=True)
        + _attempt_events(2, [3, 4], resumed=True)
    )
    s = summarize_trace(events)
    assert s["restarts"] == 1
    assert s["health"]["num_divergent"] == 4  # monotone across resume


def test_summarize_num_divergent_shard_partials_excluded():
    """Consensus-style per-shard chain_health records carry per-SHARD
    partial counts: they must not be folded as if they were run totals
    — run_end's total is the authoritative value."""
    evs = [
        {"event": "run_start", "model": "M", "kernel": "nuts"},
        {"event": "chain_health", "shard": 0, "num_divergent": 5},
        {"event": "chain_health", "shard": 1, "num_divergent": 2},
        {"event": "chain_health", "shard": 2, "num_divergent": 7},
        {"event": "chain_health", "shard": 3, "num_divergent": 1},
        {"event": "run_end", "dur_s": 1.0, "num_divergent": 15},
    ]
    events = [
        {"schema": SCHEMA_VERSION, "ts": 0.0, "wall_s": 0.0, "run": 1, **e}
        for e in evs
    ]
    assert summarize_trace(events)["health"]["num_divergent"] == 15


def test_summarize_num_divergent_ignores_unrelated_earlier_runs():
    """Two independent runs appended to one file (bench legs): the
    selected run's count never absorbs the other's."""
    events = _attempt_events(1, [9]) + _attempt_events(2, [1])
    s = summarize_trace(events)
    assert s["health"]["num_divergent"] == 1
    assert summarize_trace(events, run=1)["health"]["num_divergent"] == 9


# ---------------------------------------------------------------------------
# summarize_trace over heterogeneous inputs: rotated sequences, mixed
# schema versions, torn final lines (PR 20 satellite)
# ---------------------------------------------------------------------------


def test_summarize_trace_over_rotated_sequence(tmp_path, monkeypatch):
    """A rotated trace read back through `rotated_paths` + `iter_traces`
    summarizes as ONE story: every block lands in the phase totals, the
    `trace_rotated` markers count as ordinary auxiliary events, and the
    run_end wall survives in whichever part it rotated into."""
    monkeypatch.setenv("STARK_TRACE_MAX_MB", "0.001")
    p = str(tmp_path / "t.jsonl")
    with RunTrace(p) as tr:
        tr.emit("run_start")
        for b in range(40):
            tr.emit("sample_block", block=b, dur_s=0.01, note="x" * 64)
        tr.emit("run_end", dur_s=1.5)
    parts = telemetry.rotated_paths(p)
    assert len(parts) > 1, "rotation never triggered"
    events = list(telemetry.iter_traces(parts))
    s = summarize_trace(events)
    assert s["phases"]["sample_block"]["count"] == 40
    assert s["wall_s"] == 1.5
    assert s["events"] == len(events)
    # each fresh part opens with its rotation marker; the summary treats
    # them as known auxiliaries (not "other"/unknown)
    rotated = [e for e in events if e["event"] == "trace_rotated"]
    assert len(rotated) == len(parts) - 1
    assert s["other"] == {}


def test_summarize_trace_mixed_schema_versions():
    """One file holding records from different writer generations — a
    PR-1-era record with no envelope at all, a current-schema record,
    and a future-schema record with unknown fields — summarizes without
    raising; unknown event families degrade into ``other``, never
    silently vanish."""
    events = [
        # current writer
        {"schema": SCHEMA_VERSION, "ts": 1.0, "wall_s": 0.0, "run": 0,
         "event": "run_start", "entry": "sample"},
        {"schema": SCHEMA_VERSION, "ts": 2.0, "wall_s": 0.1, "run": 0,
         "event": "sample_block", "block": 0, "dur_s": 0.1},
        # pre-schema (PR-1-era): no schema/run/ts envelope
        {"event": "sample_block", "block": 1, "dur_s": 0.2},
        # future writer: higher schema, unknown event + fields
        {"schema": SCHEMA_VERSION + 1, "ts": 3.0, "wall_s": 0.2, "run": 0,
         "event": "quantum_block", "qubits": 8},
        {"schema": SCHEMA_VERSION, "ts": 4.0, "wall_s": 0.3, "run": 0,
         "event": "run_end", "dur_s": 0.9},
    ]
    s = summarize_trace(events)
    assert s["phases"]["sample_block"]["count"] == 2
    assert s["phases"]["sample_block"]["total_s"] == pytest.approx(0.3)
    assert s["wall_s"] == 0.9
    assert s["other"] == {"quantum_block": 1}


def test_summarize_trace_torn_final_line(tmp_path):
    """A crash mid-append leaves a torn last line; the tolerant reader
    (strict=False) skips it and the summary still covers everything
    before the tear — the strict reader refuses, loudly."""
    p = str(tmp_path / "t.jsonl")
    with RunTrace(p) as tr:
        tr.emit("run_start")
        tr.emit("sample_block", block=0, dur_s=0.4)
    with open(p, "a") as f:
        f.write('{"schema": 1, "event": "run_end", "dur_s"')  # torn
    with pytest.raises(TraceError):
        read_trace(p)
    events = read_trace(p, strict=False)
    s = summarize_trace(events)
    assert s["phases"]["sample_block"]["count"] == 1
    # the run_end never landed: the summary falls back to the event span
    assert s["wall_s"] == pytest.approx(
        events[-1]["wall_s"] - events[0]["wall_s"])
    assert s["events"] == 2


def test_summarize_trace_torn_line_inside_rotated_part(tmp_path,
                                                       monkeypatch):
    """The tear can sit in a ROTATED part (the file that was live at
    crash time is not always the live file now): `iter_traces` with
    strict=False chains past it and later parts still contribute."""
    monkeypatch.setenv("STARK_TRACE_MAX_MB", "0.001")
    p = str(tmp_path / "t.jsonl")
    with RunTrace(p) as tr:
        tr.emit("run_start")
        for b in range(40):
            tr.emit("sample_block", block=b, dur_s=0.01, note="x" * 64)
        tr.emit("run_end", dur_s=1.5)
    parts = telemetry.rotated_paths(p)
    assert len(parts) > 2
    with open(parts[1], "a") as f:
        f.write('{"event": "sample_bl')  # tear the middle part
    events = list(telemetry.iter_traces(parts, strict=False))
    s = summarize_trace(events)
    assert s["phases"]["sample_block"]["count"] == 40
    assert s["wall_s"] == 1.5

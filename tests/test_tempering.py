"""Parallel tempering tests (benchmark config 4 capability).

Oracle: a well-separated two-component 1-D mixture whose single-chain HMC
gets stuck in one mode; tempered chains must visit both modes and recover
the component weights.  Plus unit checks on the ladder and swap bookkeeping.
"""

import jax
import jax.numpy as jnp
import numpy as np

from stark_tpu.model import Model, ParamSpec
from stark_tpu.parallel.tempering import geometric_ladder, tempered_sample
import pytest


class BimodalMean(Model):
    """x ~ 0.5 N(theta, 0.5) + 0.5 N(-theta_offset + theta, ...) — simplest
    multimodal posterior: a symmetric mixture likelihood over a location."""

    def param_spec(self):
        return {"theta": ParamSpec(())}

    def log_prior(self, p):
        return jax.scipy.stats.norm.logpdf(p["theta"], 0.0, 10.0)

    def log_lik(self, p, data):
        # each row supports theta near +m or -m equally
        m = data["m"]
        a = jax.scipy.stats.norm.logpdf(data["x"], p["theta"] - m, 0.5)
        b = jax.scipy.stats.norm.logpdf(data["x"], p["theta"] + m, 0.5)
        return jnp.sum(jnp.logaddexp(a, b) - jnp.log(2.0))


def test_geometric_ladder():
    betas = geometric_ladder(8, beta_min=0.05)
    assert betas.shape == (8,)
    assert float(betas[0]) == 1.0
    np.testing.assert_allclose(float(betas[-1]), 0.05, rtol=1e-5)
    assert np.all(np.diff(np.asarray(betas)) < 0)


def test_tempered_visits_both_modes():
    # posterior over theta is bimodal at ±m (x centered at 0)
    key = jax.random.PRNGKey(0)
    data = {"x": 0.1 * jax.random.normal(key, (64,)), "m": jnp.asarray(4.0)}
    post = tempered_sample(
        BimodalMean(),
        data,
        chains=2,
        num_temps=6,
        kernel="hmc",
        num_leapfrog=8,
        num_warmup=300,
        num_samples=800,
        swap_every=2,
        seed=1,
    )
    draws = post.draws["theta"].reshape(-1)
    frac_pos = (draws > 0).mean()
    # un-tempered HMC would sit at one mode (frac ~0 or ~1)
    assert 0.15 < frac_pos < 0.85, f"stuck in one mode: frac_pos={frac_pos}"
    assert post.sample_stats["swap_accept_rate"].min() > 0.05
    # modes are at ±4ish
    assert abs(abs(draws).mean() - 4.0) < 1.0


class GaussLoc(Model):
    """d-dim Gaussian location — the BvM-regime ladder stress case.

    Between tempered posteriors the mean log-lik gap is (d/2)(1/β_hot −
    1/β_cold) (χ²_d at temperature), so at d=16 a geometric ladder to
    β=1e-2 has per-gap E[log A] ≈ −22: statistically dead by design,
    independent of row count.  (A 1-d toy CANNOT produce a dead ladder —
    measured 0.44 min-pair acceptance at β_min=1e-3 — which is why this
    test needs dimensions, not more rows.)
    """

    def __init__(self, d=16):
        self.d = d

    def param_spec(self):
        return {"theta": ParamSpec((self.d,))}

    def log_prior(self, p):
        return jnp.sum(jax.scipy.stats.norm.logpdf(p["theta"], 0.0, 10.0))

    def log_lik(self, p, data):
        return jnp.sum(jax.scipy.stats.norm.logpdf(data["x"], p["theta"], 1.0))


@pytest.mark.slow
def test_adaptive_ladder_revives_dead_swaps():
    """ΔE-matched adaptation (VERDICT r2 #8): start from a ladder whose
    rung gaps are far too wide to ever swap and check warmup swap-rate
    matching pulls every adjacent pair back to working acceptance while
    keeping the cold rung pinned at β=1."""
    from stark_tpu.parallel.tempering import geometric_ladder

    key = jax.random.PRNGKey(2)
    data = {"x": jax.random.normal(key, (256, 16))}
    kwargs = dict(
        chains=2, num_temps=4, kernel="hmc", num_leapfrog=8,
        num_warmup=600, num_samples=400, swap_every=1, seed=7,
        betas=geometric_ladder(4, beta_min=1e-2),
    )
    dead = tempered_sample(GaussLoc(16), data, **kwargs)
    live = tempered_sample(
        GaussLoc(16), data, adapt_ladder=True, ladder_adapt_rate=1.0,
        **kwargs,
    )

    dead_min = dead.sample_stats["swap_accept_per_pair"].min()
    live_min = live.sample_stats["swap_accept_per_pair"].min()
    assert dead_min < 0.02, f"ladder unexpectedly alive: {dead_min}"
    assert live_min > 0.1, f"adaptation failed to revive swaps: {live_min}"
    # cold rung stays pinned at beta=1; ladder is monotone after adaptation
    # ('betas' itself keeps the input-ladder semantics; the adapted
    # per-chain ladder lives under 'betas_adapted')
    assert live.sample_stats["betas"].shape == (4,)
    betas = live.sample_stats["betas_adapted"]
    np.testing.assert_allclose(betas[:, 0], 1.0, rtol=1e-6)
    assert np.all(np.diff(betas, axis=1) < 0)
    # the cold chain's posterior is unaffected by adaptation: theta_hat
    # shrinks the data mean by n/(n + 1/sigma0^2)
    post_mean = live.draws["theta"].mean(axis=(0, 1))
    expect = np.asarray(data["x"]).mean(axis=0) * (256 / (256 + 0.01))
    np.testing.assert_allclose(post_mean, expect, atol=0.08)


def test_gmm_init_1d_recovers_uneven_mixture():
    """EM init must find ALL components of an uneven, well-separated
    mixture — quantile/k-means seeding loses light components (which is a
    per-chain mis-allocation mode that blows up R-hat)."""
    import jax

    from stark_tpu.models import synth_gmm_data
    from stark_tpu.models.gmm import gmm_init_1d

    for seed in (0, 1):
        data, true = synth_gmm_data(
            jax.random.PRNGKey(seed), 50_000, 16, spread=4.0
        )
        init = gmm_init_1d(np.asarray(data["x"]), 16)
        err = np.abs(init["mu"] - np.asarray(true["mu"])).max()
        assert err < 0.5, (seed, err)
        assert np.all(np.diff(init["mu"]) > 0)  # Ordered-bijector ready
        np.testing.assert_allclose(init["weights"].sum(), 1.0, rtol=1e-5)


def test_tempered_on_mesh():
    from stark_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": 4, "chains": 2})
    key = jax.random.PRNGKey(3)
    data = {"x": 0.1 * jax.random.normal(key, (32,)), "m": jnp.asarray(3.0)}
    post = tempered_sample(
        BimodalMean(),
        data,
        chains=2,
        num_temps=4,
        kernel="hmc",
        num_leapfrog=8,
        num_warmup=100,
        num_samples=100,
        swap_every=2,
        seed=4,
        mesh=mesh,
    )
    assert post.draws["theta"].shape == (2, 100)
    assert np.all(np.isfinite(post.draws["theta"]))

"""Geweke + SBC oracles (SURVEY.md §5): pass on a correct setup, and have
the power to flag a broken one."""

import jax
import jax.numpy as jnp
import jax.scipy.stats as jstats
import numpy as np

from stark_tpu.bijectors import Exp
from stark_tpu.model import Model, ParamSpec
from stark_tpu.validate import geweke_test, sbc
import pytest

_N = 20


class NormalModel(Model):
    """mu ~ N(0, 2), sigma ~ LogNormal(0, 0.5), y_i ~ N(mu, sigma)."""

    def param_spec(self):
        return {"mu": ParamSpec(()), "sigma": ParamSpec((), Exp())}

    def log_prior(self, p):
        lp = jstats.norm.logpdf(p["mu"], 0.0, 2.0)
        lp += jstats.norm.logpdf(jnp.log(p["sigma"]), 0.0, 0.5) - jnp.log(p["sigma"])
        return lp

    def log_lik(self, p, data):
        return jnp.sum(jstats.norm.logpdf(data["y"], p["mu"], p["sigma"]))


def _sample_prior(key):
    k1, k2 = jax.random.split(key)
    return {
        "mu": 2.0 * jax.random.normal(k1, ()),
        "sigma": jnp.exp(0.5 * jax.random.normal(k2, ())),
    }


def _simulate(key, params):
    return {"y": params["mu"] + params["sigma"] * jax.random.normal(key, (_N,))}


def test_geweke_passes_on_correct_kernel():
    res = geweke_test(
        NormalModel(), _sample_prior, _simulate, jax.random.PRNGKey(0),
        num_iters=1500, thin=5, step_size=0.2, num_leapfrog=8,
    )
    assert res.max_abs_z() < 4.5, res.zscores


def test_geweke_flags_mismatched_generative():
    """Power check: a prior/generative mismatch must blow up the z-scores."""

    def wrong_prior(key):  # draws mu ~ N(0, 4) while the model says N(0, 2)
        p = _sample_prior(key)
        return {**p, "mu": 2.0 * p["mu"]}

    res = geweke_test(
        NormalModel(), wrong_prior, _simulate, jax.random.PRNGKey(0),
        num_iters=1500, thin=5, step_size=0.2, num_leapfrog=8,
    )
    assert res.max_abs_z() > 6.0, res.zscores


@pytest.mark.slow  # >=8s on the 1-core host (pytest.ini policy, re-profiled 2026-08-03)
def test_sbc_ranks_uniform():
    res = sbc(
        NormalModel(), _sample_prior, _simulate, jax.random.PRNGKey(1),
        num_replicates=96, num_bins=8,
        kernel="nuts", max_tree_depth=6, num_warmup=300, num_samples=255,
        thin=4,
    )
    # chi2(7) 99.9% quantile ~= 24.3; a broken sampler lands far above
    stats = res.chi2()
    assert max(stats.values()) < 25.0, stats
    # sanity: ranks span the full [0, L] range rather than collapsing
    for r in res.ranks.values():
        assert int(np.min(r)) >= 0 and int(np.max(r)) <= 255
        assert np.ptp(r) > 100


# ---- distribution-level oracles on the PRODUCTION fused likelihood ----
# The flagship path runs FusedHierLogistic through the Pallas kernel with
# custom_vjp (gradients) and custom_vmap (chain batching).  Gradient parity
# is unit-tested in test_ops_fused; these tests cover the same code with
# the Geweke/SBC joint-distribution oracles so a subtly wrong VJP or
# batching rule shows up as a posterior-level miscalibration.

# small N: Geweke's successive chain explores theta ACROSS the prior via
# data redraws; a large informative dataset pins the per-redraw posterior
# (sd(alpha0|y) << prior sd 5) and the chain cannot traverse the prior in
# any reasonable budget — that shows up as z ~ 10+ on alpha0 for the
# autodiff and fused models IDENTICALLY, i.e. a test-setup artifact
_FN, _FD, _FG = 32, 3, 4
_fx = jax.random.normal(jax.random.PRNGKey(42), (_FN, _FD))
_fg = jax.random.randint(jax.random.PRNGKey(43), (_FN,), 0, _FG)


def _fused_prior(key):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "beta": 2.5 * jax.random.normal(k1, (_FD,)),
        "alpha0": 5.0 * jax.random.normal(k2, ()),
        "sigma_alpha": jnp.abs(jax.random.normal(k3, ())),  # half-normal(1)
        "alpha_raw": jax.random.normal(k4, (_FG,)),
    }


def _fused_simulate(key, p):
    alpha = p["alpha0"] + p["sigma_alpha"] * p["alpha_raw"]
    logits = _fx @ p["beta"] + alpha[_fg]
    y = (jax.random.uniform(key, (_FN,)) < jax.nn.sigmoid(logits)).astype(
        jnp.float32
    )
    return {"x": _fx, "g": _fg, "y": y}


@pytest.mark.slow
def test_geweke_fused_hier_logistic():
    from stark_tpu.models import FusedHierLogistic

    res = geweke_test(
        FusedHierLogistic(num_features=_FD, num_groups=_FG),
        _fused_prior, _fused_simulate, jax.random.PRNGKey(2),
        num_iters=800, thin=8, step_size=0.2, num_leapfrog=8,
    )
    assert res.max_abs_z() < 5.0, res.zscores


@pytest.mark.slow
def test_sbc_fused_hier_logistic():
    from stark_tpu.models import FusedHierLogistic

    res = sbc(
        FusedHierLogistic(num_features=_FD, num_groups=_FG),
        _fused_prior, _fused_simulate, jax.random.PRNGKey(3),
        num_replicates=64, num_bins=8,
        kernel="hmc", num_leapfrog=8, num_warmup=200, num_samples=127,
        thin=2,
    )
    stats = res.chi2()
    # chi2(7) 99.9% quantile ~= 24.3
    assert max(stats.values()) < 25.0, stats
    for r in res.ranks.values():
        # span check: a collapsed/stuck sampler bunches ranks; uniform
        # ranks over [0, 127] must cover most of the range
        assert np.ptp(r) > 90, (int(np.min(r)), int(np.max(r)))


@pytest.mark.slow
def test_sbc_cox_ph():
    """SBC on the Breslow partial likelihood with CONTINUOUS times.

    Continuous times only: with heavy ties Breslow's denominator is a
    known-biased approximation of the tied-event likelihood, and SBC
    correctly flags that statistical bias (measured chi2 ~ 125 with
    8-per-unit discretized times) — an estimator property, not an
    implementation bug.  The implementation's tie-block handling is
    pinned exactly by test_cox_breslow_ties_match_reference (O(N^2)
    reference); this test covers the sampler+likelihood calibration in
    the regime where the partial likelihood is the right estimator.
    """
    from stark_tpu.models import CoxPH

    _n, _d = 96, 2
    x_fix = jax.random.normal(jax.random.PRNGKey(44), (_n, _d))

    def prior(key):
        return {"beta": 2.5 * jax.random.normal(key, (_d,))}

    def simulate(key, p):
        k1, k2 = jax.random.split(key)
        rate = jnp.exp(x_fix @ p["beta"])
        t = jax.random.exponential(k1, (_n,)) / rate
        event = (jax.random.uniform(k2, (_n,)) > 0.3).astype(jnp.float32)
        return {"x": x_fix, "t": t, "event": event}

    res = sbc(
        CoxPH(num_features=_d), prior, simulate, jax.random.PRNGKey(5),
        num_replicates=64, num_bins=8,
        kernel="hmc", num_leapfrog=8, num_warmup=200, num_samples=127,
        thin=2,
    )
    stats = res.chi2()
    assert max(stats.values()) < 25.0, stats


def test_ensure_live_platform_refuses_late_call(monkeypatch):
    """ADVICE r4 (platform.py): when the probe fails but jax has already
    initialized a NON-CPU backend in this process, the CPU fallback cannot
    take effect — ensure_live_platform must raise instead of returning as
    if it worked (the next jax call would hang on the dead relay).  A
    process already landed on CPU re-enters idempotently instead."""
    import jax
    import pytest

    from stark_tpu import platform as plat

    jax.devices()  # force backend init in this (CPU-forced) test process
    monkeypatch.setenv("JAX_PLATFORMS", "axon")  # a non-CPU platform was wanted
    monkeypatch.setattr(plat, "probe_accelerator", lambda timeout=None: False)
    # backend initialized but it IS cpu: the fallback is already in
    # effect — idempotent re-entry, not a crash of a healthy process
    assert plat.ensure_live_platform() is True
    # backend initialized and NOT cpu: fail loud, never hang later
    monkeypatch.setattr(jax, "default_backend", lambda: "axon")
    with pytest.raises(RuntimeError, match="already initialized"):
        plat.ensure_live_platform()

"""Watchdog deadman (stark_tpu/watchdog.py): beats hold it off, silence
fires it, and the interrupt handshake never eats a genuine Ctrl-C."""

import threading
import time

import pytest

from stark_tpu import telemetry
from stark_tpu.watchdog import StallError, Watchdog, watched


def test_beats_prevent_firing():
    fired = threading.Event()
    wd = Watchdog(0.3, poll_s=0.05, on_stall=fired.set)
    wd.start()
    try:
        for _ in range(10):
            time.sleep(0.05)
            wd.beat()
        assert not fired.is_set()
        assert not wd.consume_stall()
    finally:
        wd.stop()


def test_silence_fires_and_sets_stall_flag():
    fired = threading.Event()
    wd = Watchdog(0.15, poll_s=0.05, on_stall=fired.set)
    wd.start()
    try:
        assert fired.wait(2.0), "watchdog never fired on silence"
        assert wd.consume_stall()
        assert not wd.consume_stall()  # flag is consumed, not sticky
        assert wd.stall_count >= 1
    finally:
        wd.stop()


def test_progress_listener_feeds_the_watchdog():
    """telemetry.notify_progress — the beat every runner block emits —
    must reach a started watchdog with no extra wiring."""
    fired = threading.Event()
    wd = Watchdog(0.3, poll_s=0.05, on_stall=fired.set)
    wd.start()
    try:
        for _ in range(10):
            time.sleep(0.05)
            telemetry.notify_progress()
        assert not fired.is_set()
    finally:
        wd.stop()
    # after stop() the listener is unregistered
    assert wd.beat not in telemetry._PROGRESS_LISTENERS


def test_default_on_stall_interrupts_main_thread():
    """The default abort is interrupt_main: a stalled main thread sees
    KeyboardInterrupt, which supervision converts via consume_stall."""
    wd = Watchdog(0.2, poll_s=0.05)
    wd.start()
    try:
        with pytest.raises(KeyboardInterrupt):
            time.sleep(5.0)  # the "stall": no beats flow
        assert wd.consume_stall()
    finally:
        wd.stop()


def test_stall_on_worker_thread_interrupts_that_thread():
    """A watchdog started from a worker thread must abort THAT thread —
    never shoot the process main loop with a SIGINT it can't handle."""
    out = {}

    def worker():
        wd = Watchdog(0.2, poll_s=0.05)
        wd.start()
        try:
            deadline = time.monotonic() + 8.0
            while time.monotonic() < deadline:  # Python-level stall
                pass
            out["result"] = "never interrupted"
        except KeyboardInterrupt:
            out["result"] = "interrupted"
            out["stalled"] = wd.consume_stall()
        finally:
            wd.stop()

    t = threading.Thread(target=worker)
    t.start()
    t.join(timeout=12.0)
    assert out.get("result") == "interrupted"
    assert out.get("stalled") is True


def test_watched_contextmanager_none_deadline():
    with watched(None) as wd:
        assert wd is None
    with watched(0.5, poll_s=0.05) as wd:
        assert isinstance(wd, Watchdog)
        wd.beat()
    assert wd.beat not in telemetry._PROGRESS_LISTENERS


def test_bad_deadline_rejected():
    with pytest.raises(ValueError):
        Watchdog(0.0)

"""The shared fused value-and-grad layer across the model zoo
(ops/precision.py scaffold + ops/{lmm,irt,ordinal,robust}_fused.py):
per-op fused-vs-autodiff parity, knob-off bit-identity with the
historical models, mid-process precision retrace, bf16-band parity, and
a fleet smoke over a fused-layout model.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import stark_tpu
from stark_tpu.model import flatten_model, prepare_model_data
from stark_tpu.models import (
    FusedIRT2PL,
    FusedLMM,
    FusedOrderedLogistic,
    FusedStudentTRegression,
    IRT2PL,
    LinearMixedModel,
    OrderedLogistic,
    StudentTRegression,
    synth_irt_data,
    synth_lmm_data,
    synth_ordinal_data,
    synth_studentt_data,
)

KEY = jax.random.PRNGKey(0)


def _lmm_case():
    data, _ = synth_lmm_data(KEY, 600, 5, 40)
    return LinearMixedModel(5, 40), FusedLMM(5, 40), data, "STARK_FUSED_LMM"


def _irt_case():
    data, _ = synth_irt_data(KEY, 40, 15)
    return IRT2PL(40, 15), FusedIRT2PL(40, 15), data, "STARK_FUSED_IRT"


def _ordinal_case():
    data, _ = synth_ordinal_data(KEY, 600, 5, num_categories=4)
    return (
        OrderedLogistic(5, 4), FusedOrderedLogistic(5, 4), data,
        "STARK_FUSED_ORDINAL",
    )


def _robust_case():
    data, _ = synth_studentt_data(KEY, 600, 5)
    return (
        StudentTRegression(5), FusedStudentTRegression(5), data,
        "STARK_FUSED_ROBUST",
    )


CASES = {
    "lmm": _lmm_case,
    "irt": _irt_case,
    "ordinal": _ordinal_case,
    "robust": _robust_case,
}


@pytest.fixture(params=sorted(CASES))
def zoo_case(request):
    return (request.param,) + CASES[request.param]()


def test_value_and_grad_parity(zoo_case, monkeypatch):
    """Knob ON: fused potential+grad match autodiff through the plain
    model over a spread of parameter points (typical set + excursions),
    at tight f32 tolerance."""
    _name, plain, fused, data, knob = zoo_case
    monkeypatch.setenv(knob, "1")
    fm_p, fm_f = flatten_model(plain), flatten_model(fused)
    dp = prepare_model_data(plain, data)
    df = prepare_model_data(fused, data)
    for s in range(5):
        z = 0.4 * s * jax.random.normal(jax.random.PRNGKey(s), (fm_p.ndim,))
        vp, gp = fm_p.potential_and_grad(z, dp)
        vf, gf = fm_f.potential_and_grad(z, df)
        np.testing.assert_allclose(vp, vf, rtol=1e-5, atol=1e-4)
        scale = float(jnp.max(jnp.abs(gp))) + 1e-6
        np.testing.assert_allclose(
            np.asarray(gf) / scale, np.asarray(gp) / scale,
            rtol=1e-4, atol=2e-5,
        )


def test_knob_off_bit_identity(zoo_case):
    """Knob OFF (the default): the Fused* variant IS the historical
    model — same prepared data pytree, bit-identical potential and
    gradient (not just close: the fallback must route through the very
    same computation)."""
    _name, plain, fused, data, knob = zoo_case
    assert os.environ.get(knob) is None  # default-off contract
    fm_p, fm_f = flatten_model(plain), flatten_model(fused)
    dp = prepare_model_data(plain, data)
    df = prepare_model_data(fused, data)
    assert jax.tree.structure(dp) == jax.tree.structure(df)
    assert "xT" not in df and "y_grid" not in df
    z = 0.3 * jax.random.normal(jax.random.PRNGKey(7), (fm_p.ndim,))
    vp, gp = jax.jit(fm_p.potential_and_grad)(z, dp)
    vf, gf = jax.jit(fm_f.potential_and_grad)(z, df)
    assert np.asarray(vp).tobytes() == np.asarray(vf).tobytes()
    assert np.asarray(gp).tobytes() == np.asarray(gf).tobytes()


def test_knob_off_after_fused_prepare(zoo_case, monkeypatch):
    """Data prepared under the fused layout keeps working when the knob
    flips off (autodiff fallback on the same layout) — the warm-start /
    resume porting contract."""
    _name, plain, fused, data, knob = zoo_case
    monkeypatch.setenv(knob, "1")
    fm_p, fm_f = flatten_model(plain), flatten_model(fused)
    dp = prepare_model_data(plain, data)
    df = prepare_model_data(fused, data)  # fused layout
    z = 0.3 * jax.random.normal(jax.random.PRNGKey(3), (fm_p.ndim,))
    monkeypatch.setenv(knob, "0")
    v0, g0 = fm_f.potential_and_grad(z, df)  # autodiff on fused layout
    vp, gp = fm_p.potential_and_grad(z, dp)
    np.testing.assert_allclose(v0, vp, rtol=1e-5, atol=1e-4)
    scale = float(jnp.max(jnp.abs(gp))) + 1e-6
    np.testing.assert_allclose(
        np.asarray(g0) / scale, np.asarray(gp) / scale,
        rtol=1e-4, atol=2e-5,
    )


def test_bf16_band_parity(zoo_case, monkeypatch):
    """STARK_FUSED_X_DTYPE=bf16: the fused path agrees with autodiff on
    the SAME bf16-rounded design matrix within the documented mid band
    (the rounding is a data change, not an arithmetic error)."""
    _name, plain, fused, data, knob = zoo_case
    monkeypatch.setenv(knob, "1")
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "bf16")
    fm_f = flatten_model(fused)
    df = prepare_model_data(fused, data)
    if "xT" in df:
        assert df["xT"].dtype == jnp.bfloat16
    monkeypatch.setenv("STARK_FUSED_X_DTYPE", "f32")
    fm_p = flatten_model(plain)
    ref = dict(data)
    if "x" in ref:
        ref["x"] = (
            jnp.asarray(ref["x"]).astype(jnp.bfloat16).astype(jnp.float32)
        )
    dp = prepare_model_data(plain, ref)
    z = 0.3 * jax.random.normal(jax.random.PRNGKey(5), (fm_p.ndim,))
    vp, gp = fm_p.potential_and_grad(z, dp)
    vf, gf = fm_f.potential_and_grad(z, df)
    np.testing.assert_allclose(vp, vf, rtol=5e-3, atol=1e-2)
    scale = float(jnp.max(jnp.abs(gp))) + 1e-6
    np.testing.assert_allclose(
        np.asarray(gf) / scale, np.asarray(gp) / scale,
        rtol=2e-2, atol=2e-2,
    )


_VG_ENTRIES = {
    "lmm": ("stark_tpu.ops.lmm_fused", "lmm_loglik_value_and_grad"),
    "irt": ("stark_tpu.ops.irt_fused", "irt_grid_loglik_value_and_grad"),
    "ordinal": (
        "stark_tpu.ops.ordinal_fused", "ordinal_loglik_value_and_grad"
    ),
    "robust": (
        "stark_tpu.ops.robust_fused", "studentt_loglik_value_and_grad"
    ),
}


def _vg_args(name, fused, data, monkeypatch, knob):
    monkeypatch.setenv(knob, "1")
    df = prepare_model_data(fused, data)
    if name == "lmm":
        g, q = fused.num_groups, fused.num_random
        return (
            jnp.zeros((fused.num_features,)), jnp.zeros((g, q)),
            jnp.asarray(0.1), jnp.asarray(1.0),
            df["xT"], df["z"], df["g"], df["y"],
        )
    if name == "irt":
        return (
            jnp.zeros((fused.num_persons,)),
            jnp.ones((fused.num_items,)),
            jnp.zeros((fused.num_items,)),
            df["y_grid"],
        )
    if name == "ordinal":
        k = fused.num_categories
        return (
            jnp.zeros((fused.num_features,)),
            jnp.linspace(-1.0, 1.0, k - 1),
            df["xT"], df["y"],
        )
    return (
        jnp.zeros((fused.num_features,)), jnp.asarray(1.0),
        jnp.asarray(5.0), df["xT"], df["y"],
    )


def test_precision_statics_force_retrace(zoo_case, monkeypatch):
    """Toggling STARK_FUSED_PRECISION mid-process produces a fresh
    executable for every zoo op's direct entry (the shared call-time-
    static cache key from ops/precision.py), never a stale reuse."""
    import importlib

    name, _plain, fused, data, knob = zoo_case
    mod, attr = _VG_ENTRIES[name]
    vg = getattr(importlib.import_module(mod), attr)
    args = _vg_args(name, fused, data, monkeypatch, knob)
    monkeypatch.delenv("STARK_FUSED_PRECISION", raising=False)
    before = vg._jit._cache_size()
    val, grads = vg(*args)
    assert np.isfinite(float(val)) and len(grads) >= 2
    mid = vg._jit._cache_size()
    monkeypatch.setenv("STARK_FUSED_PRECISION", "default")
    vg(*args)
    after = vg._jit._cache_size()
    assert mid >= before
    assert after == mid + 1  # new static key -> new trace


def test_custom_vjp_one_pass(zoo_case, monkeypatch):
    """jax.grad through each fused op equals the one-pass direct grads
    (the scaffold's VJP chains, never recomputes)."""
    import importlib

    name, _plain, fused, data, knob = zoo_case
    mod_name, attr = _VG_ENTRIES[name]
    mod = importlib.import_module(mod_name)
    vg = getattr(mod, attr)
    op = getattr(mod, attr.replace("_value_and_grad", ""))
    args = _vg_args(name, fused, data, monkeypatch, knob)
    _val, grads = vg(*args)
    g_vjp = jax.grad(op, argnums=tuple(range(len(grads))))(*args)
    for direct, chained in zip(grads, g_vjp):
        np.testing.assert_allclose(direct, chained, rtol=1e-6, atol=1e-7)


def test_irt_ragged_triples_fused(monkeypatch):
    """Incomplete response sets (no dense grid) keep the triple layout
    and still take the fused scatter path, matching autodiff."""
    plain, fused, data, knob = _irt_case()
    keep = np.arange(len(np.asarray(data["y"]))) % 3 != 0  # drop a third
    ragged = {k: jnp.asarray(np.asarray(v)[keep]) for k, v in data.items()}
    monkeypatch.setenv(knob, "1")
    df = prepare_model_data(fused, ragged)
    assert "y_grid" not in df  # grid check must refuse the ragged set
    fm_p, fm_f = flatten_model(plain), flatten_model(fused)
    dp = prepare_model_data(plain, ragged)
    z = 0.3 * jax.random.normal(jax.random.PRNGKey(2), (fm_p.ndim,))
    vp, gp = fm_p.potential_and_grad(z, dp)
    vf, gf = fm_f.potential_and_grad(z, df)
    np.testing.assert_allclose(vp, vf, rtol=1e-5)
    np.testing.assert_allclose(gp, gf, rtol=1e-4, atol=1e-4)


def test_irt_grid_layout_refuses_row_split(monkeypatch):
    """The dense (P, I) grid pins y_grid rows to theta entries: row-
    splitting entry points (SG-HMC minibatches, consensus shards, mesh
    data sharding) must fail fast on grid-prepared data instead of
    slicing y_grid against a full-length theta — while the triples
    layout (knob off, or ragged) keeps its default row axes."""
    plain, fused, data, knob = _irt_case()
    monkeypatch.setenv(knob, "1")
    df = prepare_model_data(fused, data)
    assert "y_grid" in df
    with pytest.raises(NotImplementedError, match="grid layout"):
        fused.data_row_axes(df)
    with pytest.raises(NotImplementedError, match="grid layout"):
        fused.data_shard_row_axes(df)
    # triples keep the shardable default (each triple carries its ids)
    monkeypatch.setenv(knob, "0")
    dt = prepare_model_data(fused, data)
    assert jax.tree.leaves(fused.data_row_axes(dt)) == [0] * len(dt)
    assert jax.tree.leaves(plain.data_row_axes(data)) == [0] * len(data)


@pytest.mark.slow  # >=8s on the 1-core host (pytest.ini policy, re-profiled 2026-08-03)
def test_sampling_smoke_fused_lmm(monkeypatch, tmp_path):
    """End-to-end: a fused-path model samples through the adaptive
    runner with finite draws, and the run_start + per-block grad-eval
    telemetry carries the fused= execution-path tag."""
    from stark_tpu.telemetry import RunTrace, read_trace

    monkeypatch.setenv("STARK_FUSED_LMM", "1")
    data, _ = synth_lmm_data(KEY, 400, 3, 12)
    model = FusedLMM(3, 12)
    tpath = str(tmp_path / "trace.jsonl")
    post = stark_tpu.sample_until_converged(
        model, data, chains=2, kernel="nuts", block_size=25,
        max_blocks=4, min_blocks=1, num_warmup=100, ess_target=20.0,
        rhat_target=1.5, seed=0, trace=RunTrace(tpath),
    )
    events = read_trace(tpath)
    assert np.all(np.isfinite(post.draws["beta"]))
    starts = [e for e in events if e["event"] == "run_start"]
    assert starts and starts[0]["fused"] == "lmm"
    blocks = [e for e in events if e["event"] == "sample_block"]
    assert blocks and all(b.get("fused") == "lmm" for b in blocks)
    # the plain model's trace stays untagged (byte-identity contract)
    tpath2 = str(tmp_path / "trace_plain.jsonl")
    stark_tpu.sample_until_converged(
        LinearMixedModel(3, 12), data, chains=2, kernel="nuts",
        block_size=25, max_blocks=4, min_blocks=1, num_warmup=100,
        ess_target=20.0, rhat_target=1.5, seed=0, trace=RunTrace(tpath2),
    )
    for e in read_trace(tpath2):
        assert "fused" not in e


@pytest.mark.slow  # >=8s on the 1-core host (pytest.ini policy, re-profiled 2026-08-03)
def test_fleet_smoke_fused_layout(monkeypatch):
    """One FleetSpec over a fused-layout model: per-problem prepare_data
    runs the fused transform before stacking, and every lane samples
    finite draws through the vmapped runner."""
    from stark_tpu.fleet import FleetSpec, sample_fleet

    monkeypatch.setenv("STARK_FUSED_ORDINAL", "1")
    rng = np.random.default_rng(0)
    base, _ = synth_ordinal_data(KEY, 240, 3, num_categories=4)
    base = {k: np.asarray(v) for k, v in base.items()}
    datasets = []
    for _ in range(3):
        d = dict(base)
        d["x"] = (d["x"] + rng.normal(0, 0.05, d["x"].shape)).astype(
            np.float32
        )
        datasets.append(d)
    model = FusedOrderedLogistic(3, 4)
    spec = FleetSpec.from_problems(model, datasets)
    res = sample_fleet(
        spec, chains=2, block_size=25, max_blocks=6, min_blocks=1,
        num_warmup=100, ess_target=40.0, rhat_target=1.3, seed=0,
    )
    assert len(res.problems) == 3
    for pr in res.problems:
        draws = pr.draws["beta"]
        assert draws.shape[0] == 2 and draws.shape[-1] == 3
        assert np.all(np.isfinite(draws))

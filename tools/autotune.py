#!/usr/bin/env python
"""Ledger-mining autotuner: emit a parity-gated execution profile.

The repo's ~15 performance knobs (STARK_FUSED_* family toggles, the
X-stream dtype, the MXU precision, the ragged-NUTS scheduler, the fleet
slot/warm-start/mesh trio) each shipped with their own evidence legs —
``bench.py microbench`` rows in ``bench_artifacts/ledger.jsonl``, the
``tools/precision_parity.py`` zoo grid — but nothing reconciled them
into a configuration.  This tool does, in four steps:

1. **Fingerprint** the hardware (`stark_tpu.platform.hardware_fingerprint`).
2. **Mine** the perf ledger for rows matching that fingerprint (legacy
   pre-fingerprint rows match on platform + device_kind + device_count);
   stale-schema rows and fingerprint mismatches are skipped WITH COUNTS
   — silent truncation would read as "no evidence" when the evidence was
   simply unreadable.
3. **Measure fresh** smoke-scale microbench legs for whatever the ledger
   could not answer (fused families, X-dtype legs, nutssched, the
   streaming-fleet leg) — skipped under ``--no-fresh``/``--check``.
4. **Select** the cheapest configuration whose parity cells ALL pass the
   `precision_parity` sweep grid (run here at smoke scale): per-family
   fused toggles on iff measured speedup > 1x, the X-stream dtype
   maximizing measured throughput among parity-eligible dtypes, the
   cheapest parity-passing precision (default < high < highest, with
   ``highest`` inheriting ``high``'s verdict by construction), ragged
   NUTS iff bit-identical AND faster, the fleet trio from their own
   gates.

The result is a versioned JSON profile (`stark_tpu.profile`, atomic
write) at ``bench_artifacts/profiles/<fingerprint>.json``, loaded by
default at every runner/fleet/sampler entry (STARK_PROFILE=path|auto|0;
explicit STARK_* env always wins), plus one honest-null ``autotune:*``
ledger row recording the choice (ess_per_sec is null — the autotuner
measures nothing gateable; ``converged`` carries the parity verdict).

``--check`` is the tier-1 contract smoke: no fresh measurement, a tiny
parity subset (one zoo case x {f32, bf16} x default), profile written
to a temp dir and round-tripped through `load_profile` — proving the
mine/select/emit/load pipeline end to end in seconds.

The process pins STARK_PROFILE=0 for itself: candidate measurement and
parity cells must run on raw knob defaults, never under a previously
emitted profile (an autotuner steered by its own output ratchets).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

# --- mining (pure: unit-tested without jax) ----------------------------

#: microbench family -> the fused-op toggle it evidences.  GLM has no
#: standalone microbench family (its default is on; parity still gates
#: it), logistic's fused op is always-on (no knob).
FAMILY_KNOBS = {
    "lmm": "STARK_FUSED_LMM",
    "irt": "STARK_FUSED_IRT",
    "ordinal": "STARK_FUSED_ORDINAL",
    "robust": "STARK_FUSED_ROBUST",
}

#: the dtype-scan family: X-stream dtype legs are measured on the
#: scatter/stream-dominated LMM op (the family the quantized data plane
#: was built for)
DTYPE_FAMILY = "lmm"


def mine_ledger(path, fingerprint, device_info):
    """Read the RAW ledger and split it into (matching_rows, counts).

    Unlike `stark_tpu.ledger.read_rows` (which silently skips foreign
    lines — right for the gate, wrong for an evidence miner), every
    skipped line is counted: ``torn`` (unparseable), ``stale_schema``
    (a schema other than the current writer's — regenerate, don't
    guess), ``fingerprint_mismatch`` (evidence from other hardware must
    not steer this one).  Rows predating the fingerprint column match
    on platform + device_kind + device_count from ``device_info``.
    """
    from stark_tpu.ledger import LEDGER_SCHEMA

    counts = {
        "matched": 0, "stale_schema": 0, "fingerprint_mismatch": 0,
        "torn": 0, "lines": 0,
    }
    rows = []
    try:
        f = open(path)
    except OSError:
        return rows, counts
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            counts["lines"] += 1
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                counts["torn"] += 1
                continue
            if not isinstance(rec, dict) or rec.get("schema") != LEDGER_SCHEMA:
                counts["stale_schema"] += 1
                continue
            fp = rec.get("fingerprint")
            if fp is not None:
                matched = fp == fingerprint
            else:
                matched = all(
                    rec.get(k) == device_info.get(k)
                    for k in ("platform", "device_kind", "device_count")
                )
            if not matched:
                counts["fingerprint_mismatch"] += 1
                continue
            counts["matched"] += 1
            rows.append(rec)
    return rows, counts


def _fusedvg_key(config):
    """(family, x_dtype) from a ``fusedvg:<family>:...[:x=<dtype>]`` key,
    or None for other series."""
    if not isinstance(config, str) or not config.startswith("fusedvg:"):
        return None
    parts = config.split(":")
    family = parts[1] if len(parts) > 1 else ""
    x_dtype = "f32"
    for p in parts[2:]:
        if p.startswith("x="):
            x_dtype = p[2:]
    return family, x_dtype


def structure_evidence(rows):
    """Latest-wins evidence index from matching ledger rows:

    * ``fusedvg[(family, x_dtype)]`` — fused value-and-grad rows,
    * ``nutssched`` — the ragged-scheduler row,
    * ``fleet[sched]`` — streaming-fleet rows keyed slots / compact /
      slots_warmstart,
    * ``fleet_mesh`` — the device-parallel fleet row.

    Rows are oldest-first in the ledger, so plain overwrites make the
    newest row win per key.
    """
    ev = {"fusedvg": {}, "nutssched": None, "fleet": {}, "fleet_mesh": None}
    for r in rows:
        config = r.get("config", "")
        fk = _fusedvg_key(config)
        if fk is not None:
            ev["fusedvg"][fk] = r
            continue
        if config.startswith("nutssched:"):
            ev["nutssched"] = r
        elif config.startswith("fleet:stream:"):
            for part in config.split(":"):
                if part.startswith("sched="):
                    ev["fleet"][part[len("sched="):]] = r
        elif config.startswith("fleet:mesh:"):
            ev["fleet_mesh"] = r
    return ev


def missing_fresh_legs(evidence, supported_dtypes):
    """The microbench legs a full run must measure because the mined
    ledger could not answer them: ``("fusedvg", family, x_dtype)`` for
    each family toggle and each candidate dtype of the dtype-scan
    family, ``("nutssched",)``, ``("fleet_stream",)``.  Pure — the
    fingerprint-mismatch fallback contract (mismatched history == no
    history == fresh measurement) is unit-tested on this."""
    legs = []
    for fam in FAMILY_KNOBS:
        if (fam, "f32") not in evidence["fusedvg"]:
            legs.append(("fusedvg", fam, None))
    for dt in supported_dtypes:
        if dt != "f32" and (DTYPE_FAMILY, dt) not in evidence["fusedvg"]:
            legs.append(("fusedvg", DTYPE_FAMILY, dt))
    if evidence["nutssched"] is None:
        legs.append(("nutssched",))
    if not evidence["fleet"]:
        legs.append(("fleet_stream",))
    return legs


# --- selection (pure: unit-tested without jax) -------------------------


def select_config(evidence, parity_rows, supported_dtypes):
    """The cheapest parity-passing knob configuration given the
    evidence.  Returns ``(knobs, parity, rationale)`` where ``knobs``
    is the CANDIDATE_SPACE-valued dict the profile carries, ``parity``
    the verdict dict recorded in (and re-checked at every load of) the
    profile, ``rationale`` the per-knob evidence summary for the
    artifact/ledger row.

    Parity eligibility is per (x_dtype, precision) cell set: a dtype or
    precision with ANY failing zoo cell — or with no coverage at all in
    the grid that ran — is ineligible.  ``highest`` inherits ``high``'s
    verdict (more internal precision than the band was calibrated
    against, by design) and is never selected (never cheapest).
    """

    def cells(d, p):
        if p == "highest":
            p = "high"
        return [
            r for r in parity_rows
            if r.get("x_dtype") == d and r.get("precision") == p
        ]

    def eligible(d, p):
        cs = cells(d, p)
        return bool(cs) and all(r.get("ok") for r in cs)

    rationale = {}
    knobs = {}

    # per-family fused toggles: on iff measured fused-vs-autodiff
    # speedup beats 1x (missing evidence -> the built-in default: off).
    # GLM's built-in default is ON and it has no microbench family; it
    # stays on, gated by its parity cells like every other op.
    knobs["STARK_FUSED_GLM"] = "1"
    for fam, knob in FAMILY_KNOBS.items():
        row = evidence["fusedvg"].get((fam, "f32"))
        sp = row.get("speedup_vs_autodiff") if row else None
        on = bool(sp is not None and sp > 1.0)
        knobs[knob] = "1" if on else "0"
        rationale[knob] = {"speedup_vs_autodiff": sp}

    # X-stream dtype: the measured throughput ratio of the dtype-scan
    # family's fused op at dtype d over its f32 stream, restricted to
    # parity-eligible dtypes; ratios within 5% of f32 stay f32 (a wash
    # must not buy precision risk)
    base = evidence["fusedvg"].get((DTYPE_FAMILY, "f32"))
    best_d, best_ratio = "f32", 1.0
    dtype_ratios = {}
    for d in supported_dtypes:
        if d == "f32":
            continue
        if not (eligible(d, "default") or eligible(d, "high")):
            continue
        row = evidence["fusedvg"].get((DTYPE_FAMILY, d))
        if row is None:
            continue
        ratio = None
        rate_d = row.get("ess_per_sec") or row.get("value")
        rate_0 = (base or {}).get("ess_per_sec") or (base or {}).get("value")
        if rate_d and rate_0:
            ratio = rate_d / rate_0
        elif row.get("speedup_vs_f32x"):
            ratio = row["speedup_vs_f32x"]
        if ratio is None:
            continue
        dtype_ratios[d] = round(ratio, 3)
        if ratio > max(best_ratio * 1.05, 1.05):
            best_d, best_ratio = d, ratio
    if not (eligible(best_d, "default") or eligible(best_d, "high")):
        # the winning dtype lost parity (or f32 itself has no passing
        # precision): fall back to f32 before failing outright
        best_d, best_ratio = "f32", 1.0
    knobs["STARK_FUSED_X_DTYPE"] = best_d
    rationale["STARK_FUSED_X_DTYPE"] = {
        "ratios_vs_f32": dtype_ratios, "chosen_ratio": round(best_ratio, 3),
    }

    # precision: cheapest parity-passing for the chosen dtype
    precision, parity_ok = None, False
    for p in ("default", "high"):
        if eligible(best_d, p):
            precision, parity_ok = p, True
            break
    knobs["STARK_FUSED_PRECISION"] = precision or "high"

    # ragged NUTS: bit identity is the admission ticket, speedup the
    # reason (either missing -> the safe default: legacy scheduling)
    ns = evidence["nutssched"]
    ragged = bool(
        ns
        and ns.get("bit_identical")
        and (ns.get("speedup_vs_legacy") or 0) > 1.0
    )
    knobs["STARK_RAGGED_NUTS"] = "1" if ragged else "0"
    rationale["STARK_RAGGED_NUTS"] = {
        "bit_identical": ns.get("bit_identical") if ns else None,
        "speedup_vs_legacy": ns.get("speedup_vs_legacy") if ns else None,
    }

    # fleet trio, each from its own committed gate vocabulary
    slots = evidence["fleet"].get("slots")
    compact = evidence["fleet"].get("compact")
    slots_on = bool(
        slots
        and slots.get("converged")
        and slots.get("ess_per_sec") is not None
        and (
            compact is None
            or compact.get("ess_per_sec") is None
            or slots["ess_per_sec"] >= compact["ess_per_sec"]
        )
    )
    knobs["STARK_FLEET_SLOTS"] = "1" if slots_on else "0"
    ws = evidence["fleet"].get("slots_warmstart")
    ws_speedup = ws.get("warmstart_speedup") if ws else None
    knobs["STARK_FLEET_WARMSTART"] = (
        "1" if slots_on and ws_speedup is not None and ws_speedup > 1.0
        else "0"
    )
    mesh = evidence["fleet_mesh"]
    mesh_on = bool(
        mesh
        and mesh.get("converged")
        and (mesh.get("speedup_vs_single_device") or 0) >= 2.0
    )
    knobs["STARK_FLEET_MESH"] = "1" if mesh_on else "0"
    rationale["STARK_FLEET_SLOTS"] = {
        "slots_rate": slots.get("ess_per_sec") if slots else None,
        "compact_rate": compact.get("ess_per_sec") if compact else None,
    }
    rationale["STARK_FLEET_WARMSTART"] = {"warmstart_speedup": ws_speedup}
    rationale["STARK_FLEET_MESH"] = {
        "speedup_vs_single_device": (
            mesh.get("speedup_vs_single_device") if mesh else None
        ),
    }

    chosen = cells(best_d, knobs["STARK_FUSED_PRECISION"])
    parity = {
        "ok": parity_ok,
        "x_dtype": best_d,
        "precision": knobs["STARK_FUSED_PRECISION"],
        "cells": len(chosen),
        "failed": sorted(
            f"{r.get('op')}:{r.get('x_dtype')}:{r.get('precision')}"
            for r in chosen if not r.get("ok")
        ),
    }
    return knobs, parity, rationale


# --- measurement / orchestration ---------------------------------------


def _run_parity(check):
    """The smoke-scale parity grid for this run: (rows, scale dict).
    ``--check`` shrinks to one zoo case x {f32, bf16} x default — the
    harness-pipeline smoke; the full run covers every case and dtype at
    PARITY_SWEEP_* smoke scale (overridable via env, as everywhere)."""
    if check:
        for k, v in (("PARITY_SWEEP_N", "512"), ("PARITY_SWEEP_D", "4"),
                     ("PARITY_SWEEP_G", "20")):
            os.environ.setdefault(k, v)
    else:
        for k, v in (("PARITY_SWEEP_N", "4000"), ("PARITY_SWEEP_D", "8"),
                     ("PARITY_SWEEP_G", "50")):
            os.environ.setdefault(k, v)
    import importlib

    import precision_parity

    importlib.reload(precision_parity)  # constants are read at import
    scale = {
        "n": precision_parity.SWEEP_N,
        "d": precision_parity.SWEEP_D,
        "g": precision_parity.SWEEP_G,
    }
    if check:
        cases = precision_parity.zoo_cases()[:1]
        rows, _ = precision_parity.run_sweep(
            x_dtypes=("f32", "bf16"), precisions=("default",), cases=cases,
        )
    else:
        rows, _ = precision_parity.run_sweep()
    return rows, scale


def _measure_fresh(legs):
    """Run the smoke-scale microbench legs the ledger could not answer
    and fold their rows into the evidence index shape.  Each leg is
    best-effort: a broken leg records nothing (its knob then keeps the
    built-in default), never aborts the tune."""
    os.environ.setdefault("BENCH_FUSEDVG_SCALE", "0.05")
    os.environ.setdefault("BENCH_NUTSSCHED_SCALE", "0.25")
    from bench import res_row
    from stark_tpu import benchmarks as bmarks

    fresh = {"fusedvg": {}, "nutssched": None, "fleet": {}}
    ran = []
    for leg in legs:
        try:
            if leg[0] == "fusedvg":
                _, fam, xdt = leg
                row = res_row(
                    bmarks.bench_fused_value_and_grad(fam, x_dtype=xdt)
                )
                row["ess_per_sec"] = row.get("value")
                fresh["fusedvg"][(fam, xdt or "f32")] = row
            elif leg[0] == "nutssched":
                row = res_row(bmarks.bench_nuts_sched())
                fresh["nutssched"] = row
            elif leg[0] == "fleet_stream":
                r = bmarks.bench_fleet_stream(
                    problems=4, chains=2, num_warmup=100, block_size=20,
                    max_blocks=20, ess_target=30.0, max_batch=2,
                )
                row = res_row(r)
                row["ess_per_sec"] = row.get("value")
                fresh["fleet"]["slots"] = row
                legacy = row.get("legacy") or {}
                if legacy:
                    fresh["fleet"]["compact"] = legacy
                ws = row.get("warmstart") or {}
                if ws:
                    fresh["fleet"]["slots_warmstart"] = ws
            ran.append(":".join(str(p) for p in leg if p))
        except Exception as e:  # noqa: BLE001 — one broken leg must not
            # abort the tune; its knob keeps the built-in default
            print(f"[autotune] fresh leg {leg} failed: {e!r}",
                  file=sys.stderr)
    return fresh, ran


def _merge_evidence(mined, fresh):
    """Fresh measurement fills only the holes — a mined row from THIS
    fingerprint is real history and outranks a smoke-scale fresh leg."""
    out = {
        "fusedvg": {**fresh["fusedvg"], **mined["fusedvg"]},
        "nutssched": mined["nutssched"] or fresh["nutssched"],
        "fleet": {**fresh["fleet"], **mined["fleet"]},
        "fleet_mesh": mined.get("fleet_mesh"),
    }
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--check", action="store_true",
        help="tier-1 contract smoke: no fresh measurement, tiny parity "
        "subset, profile written to a temp dir and round-trip loaded",
    )
    ap.add_argument(
        "--no-fresh", action="store_true",
        help="mine + parity only; never run fresh microbench legs",
    )
    ap.add_argument(
        "--model", default="hier_logistic",
        help="model tag recorded in the profile (default: the flagship)",
    )
    ap.add_argument(
        "--out", default=None,
        help="profile path (default: bench_artifacts/profiles/"
        "<fingerprint>.json; --check defaults to a temp dir)",
    )
    ap.add_argument(
        "--ledger", default=None,
        help="ledger to mine (default: the STARK_PERF_LEDGER resolution)",
    )
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    # the autotuner must measure RAW knob defaults: never run candidate
    # legs (or parity cells) under a previously emitted profile
    os.environ["STARK_PROFILE"] = "0"

    from stark_tpu.platform import ensure_live_platform, hardware_fingerprint

    ensure_live_platform()
    from stark_tpu import ledger, profile, telemetry

    fingerprint = hardware_fingerprint()
    info = telemetry.device_info()
    from stark_tpu.platform import _dtype_support

    backend_ok = set(_dtype_support())
    supported = [
        d for d in profile.CANDIDATE_SPACE["STARK_FUSED_X_DTYPE"]
        if d in backend_ok
    ]

    ledger_path = args.ledger or ledger.default_ledger_path() or os.path.join(
        REPO, "bench_artifacts", "ledger.jsonl"
    )
    mined_rows, counts = mine_ledger(ledger_path, fingerprint, info)
    mined = structure_evidence(mined_rows)
    print(
        f"[autotune] ledger {ledger_path}: {counts['matched']} matching "
        f"row(s) ({counts['stale_schema']} stale-schema, "
        f"{counts['fingerprint_mismatch']} fingerprint-mismatch, "
        f"{counts['torn']} torn line(s) skipped)",
        file=sys.stderr,
    )

    fresh_ran = []
    if args.check or args.no_fresh:
        evidence = _merge_evidence(
            mined, {"fusedvg": {}, "nutssched": None, "fleet": {}}
        )
    else:
        legs = missing_fresh_legs(mined, supported)
        fresh, fresh_ran = _measure_fresh(legs)
        evidence = _merge_evidence(mined, fresh)

    parity_rows, parity_scale = _run_parity(args.check)
    knobs, parity, rationale = select_config(evidence, parity_rows, supported)
    parity["scale"] = parity_scale

    out_path = args.out
    if out_path is None and args.check:
        out_path = os.path.join(
            tempfile.mkdtemp(prefix="autotune_check_"),
            f"{fingerprint}.json",
        )

    summary = {
        "fingerprint": fingerprint,
        "knobs": knobs,
        "parity_ok": parity["ok"],
        "parity_failed": parity["failed"],
        "mined_rows": counts["matched"],
        "stale_rows_skipped": counts["stale_schema"],
        "fingerprint_mismatch_rows": counts["fingerprint_mismatch"],
        "fresh_legs": fresh_ran,
        "wall_s": round(time.perf_counter() - t0, 1),
    }

    if not parity["ok"]:
        # no profile: an emitted-but-refused-at-load profile would be
        # dead weight, and a silently applied parity-failing one is the
        # exact failure mode the gate exists to prevent
        summary["profile"] = None
        print(json.dumps(summary, indent=1))
        print("[autotune] FAILED: no parity-passing configuration",
              file=sys.stderr)
        return 1

    prof = profile.new_profile(
        fingerprint=fingerprint,
        knobs=knobs,
        model=args.model,
        parity=parity,
        evidence={
            "rationale": rationale,
            "mined_rows": counts["matched"],
            "stale_rows_skipped": counts["stale_schema"],
            "fingerprint_mismatch_rows": counts["fingerprint_mismatch"],
            "fresh_legs": fresh_ran,
            "ledger": ledger_path,
        },
        source="tools/autotune.py" + (" --check" if args.check else ""),
    )
    path = profile.write_profile(prof, out_path)
    loaded = profile.load_profile(path)  # round-trip: emit must load
    assert loaded["id"] == prof["id"]
    summary["profile"] = prof["id"]
    summary["path"] = path

    if not args.check:
        # one honest-null ledger row records the CHOICE: the autotuner
        # measures nothing gateable, so ess_per_sec stays null (never
        # 0.0) and ``converged`` carries the parity verdict
        row = ledger.make_row(
            source="tools/autotune.py",
            config=f"autotune:{info.get('platform', 'unknown')}",
            bench={
                "value": None,
                "converged": parity["ok"],
                "wall_s": summary["wall_s"],
                "profile": prof["id"],
            },
        )
        row.update({
            "chosen_x_dtype": knobs["STARK_FUSED_X_DTYPE"],
            "chosen_precision": knobs["STARK_FUSED_PRECISION"],
            "parity_cells": parity["cells"],
            "mined_rows": counts["matched"],
            "stale_rows_skipped": counts["stale_schema"],
            "fingerprint_mismatch_rows": counts["fingerprint_mismatch"],
            "fresh_legs": len(fresh_ran),
        })
        try:
            ledger.append_row(row, ledger_path)
            summary["ledger_row"] = True
        except Exception as e:  # noqa: BLE001 — the row is provenance,
            # not the product; a full disk must not fail the tune
            print(f"[autotune] ledger append failed: {e!r}", file=sys.stderr)
            summary["ledger_row"] = False

    print(json.dumps(summary, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())

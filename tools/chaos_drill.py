#!/usr/bin/env python
"""Standalone chaos-drill runner: the fault-injection scenario matrix.

Thin wrapper over `stark_tpu.chaos` (the same matrix the
``python -m stark_tpu chaos-drill`` subcommand runs), so the drill is
invokable from CI without the CLI's platform setup::

    python tools/chaos_drill.py                 # full matrix
    python tools/chaos_drill.py stall_watchdog  # one scenario
    python tools/chaos_drill.py --workdir /tmp/drill --list

Exit code 0 iff every scenario passes.  Scenario semantics, knobs, and the
failpoint grammar are documented in ``stark_tpu/chaos.py`` and the README
"Robustness" section.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the drill exercises supervision mechanics, not hardware: force CPU so a
# dead accelerator tunnel can't fail a drill about fault *injection*
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("scenario", nargs="*", help="scenario names (default: all)")
    parser.add_argument("--workdir", default=None, help="keep artifacts here")
    parser.add_argument("--list", action="store_true", help="list scenarios")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="[%(name)s] %(message)s", stream=sys.stderr
    )
    from stark_tpu import chaos

    if args.list:
        print("\n".join(chaos.SCENARIOS))
        return 0
    return chaos.main(args.scenario or None, args.workdir)


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Render the mesh communication report from a telemetry trace.

    python tools/comms_report.py /tmp/t.jsonl            # last run in file
    python tools/comms_report.py /tmp/t.jsonl --run 1    # a specific run
    python tools/comms_report.py /tmp/t.jsonl --all      # every run
    python tools/comms_report.py /tmp/t.jsonl --json     # machine-readable

Where ``tools/trace_report.py`` answers "what happened" and
``tools/timeline_report.py`` answers "where did the wall go", this
answers "what moved over the wire": the ``comm`` events the parallel
primitives layer (``stark_tpu.parallel.primitives``) emits for every
accounted collective — per-primitive call/byte rollups, a wire-bytes
ranking by call site (who is paying for the traffic), host-blocked wall,
and the mesh fleet's shard-imbalance trail (per-shard block walls from
``fleet_block`` events, straggler attribution, and any
``mesh_imbalance`` health warnings the balance trail raised).

Forward/backward compat: pre-PR-16 traces (and STARK_COMM_TELEMETRY=0
runs) carry no ``comm`` events — the report says so and exits 0, never
an error.  ``--json`` emits the raw rollup dict.  Stdlib-only read path
apart from `stark_tpu.telemetry` (no jax import), so it runs anywhere
the trace file lands.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# repo-root invocation without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stark_tpu.telemetry import read_trace, summarize_trace  # noqa: E402


def _fmt(v) -> str:
    # "n/a", never a crash: fields a trace predates must still render
    if v is None:
        return "n/a"
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _table(rows, header) -> str:
    """Plain aligned text table (no deps)."""
    cols = [header] + [[_fmt(c) for c in r] for r in rows]
    widths = [max(len(r[i]) for r in cols) for i in range(len(header))]
    lines = []
    for j, r in enumerate(cols):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if j == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def _bytes(v):
    if v is None:
        return None
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024.0 or unit == "GiB":
            return f"{v:.1f} {unit}" if unit != "B" else f"{int(v)} B"
        v /= 1024.0


def _median(xs):
    ws = sorted(xs)
    n = len(ws)
    return ws[n // 2] if n % 2 else 0.5 * (ws[n // 2 - 1] + ws[n // 2])


def comms_rollup(events, run):
    """The machine-readable report dict for one run (the --json shape)."""
    evs = [e for e in events if e.get("run", 0) == run]
    comm = [e for e in evs if e.get("event") == "comm"]

    by_prim = {}
    by_site = {}
    for e in comm:
        prim = str(e.get("primitive", "unknown"))
        p = by_prim.setdefault(prim, {
            "calls": 0, "payload_bytes": 0, "wire_bytes": 0,
            "host_blocked_s": 0.0, "participants_last": None,
        })
        p["calls"] += 1
        p["payload_bytes"] += int(e.get("payload_bytes") or 0)
        p["wire_bytes"] += int(e.get("wire_bytes") or 0)
        p["host_blocked_s"] = round(
            p["host_blocked_s"] + float(e.get("host_blocked_s") or 0.0), 6
        )
        if e.get("participants") is not None:
            p["participants_last"] = e["participants"]
        site = str(e.get("site", "unknown"))
        s = by_site.setdefault(site, {"calls": 0, "wire_bytes": 0})
        s["calls"] += 1
        s["wire_bytes"] += int(e.get("wire_bytes") or 0)

    # shard-imbalance trail: per-shard walls the mesh fleet stamped on
    # its fleet_block events (absent off-mesh / pre-PR-16)
    walls_rows = [
        e["shard_walls"] for e in evs
        if e.get("event") == "fleet_block" and e.get("shard_walls")
    ]
    shards = None
    if walls_rows:
        n = len(walls_rows[-1])
        rows = [w for w in walls_rows if len(w) == n]
        means = [
            sum(float(w[k]) for w in rows) / len(rows) for k in range(n)
        ]
        maxes = [max(float(w[k]) for w in rows) for k in range(n)]
        med = _median(means)
        shards = {
            "blocks_timed": len(rows),
            "mean_wall_s": [round(m, 6) for m in means],
            "max_wall_s": [round(m, 6) for m in maxes],
            "ratio_to_median": [
                round(m / med, 4) if med > 0 else None for m in means
            ],
        }
    imbalance = [
        e for e in evs
        if e.get("event") == "health_warning"
        and e.get("warning") == "mesh_imbalance"
    ]
    # elastic fault domains (PR 17): shards the deadman declared lost
    # mid-run — the wall trail above covers the mesh AS DISPATCHED, so a
    # loss event is the reader's cue that the shard axis shrank
    lost = [
        {k: e.get(k) for k in ("block", "shard", "cause",
                               "shards_before", "shards_after")}
        for e in evs if e.get("event") == "shard_lost"
    ]

    summary = summarize_trace(events, run=run)
    return {
        "run": run,
        "comms": summary.get("comms") or {},
        "by_primitive": by_prim,
        "by_site": by_site,
        "shards": shards,
        "mesh_imbalance_warnings": [
            {k: e.get(k) for k in ("block", "shard", "value", "threshold")}
            for e in imbalance
        ],
        "lost_shards": lost,
    }


def render_run(events, run) -> str:
    r = comms_rollup(events, run)
    out = [f"run {run}: communication report"]
    if not r["by_primitive"]:
        out.append(
            "(no comm events — trace predates PR 16 or ran with "
            "STARK_COMM_TELEMETRY=0; nothing to report)"
        )
        return "\n".join(out)

    cm = r["comms"]
    out.append(
        f"{cm.get('calls', 0)} accounted calls, "
        f"{_bytes(cm.get('wire_bytes')) or 'n/a'} predicted wire, "
        f"{_fmt(cm.get('host_blocked_s'))}s host-blocked"
    )
    out.append("")

    rows = [
        (
            prim,
            p["calls"],
            _bytes(p["payload_bytes"]),
            _bytes(p["wire_bytes"]),
            p["host_blocked_s"],
            p["participants_last"],
        )
        for prim, p in sorted(
            r["by_primitive"].items(),
            key=lambda kv: -kv[1]["wire_bytes"],
        )
    ]
    out.append(_table(
        rows,
        ("primitive", "calls", "payload", "wire", "host_blocked_s",
         "participants"),
    ))
    out.append("")

    rows = [
        (site, s["calls"], _bytes(s["wire_bytes"]))
        for site, s in sorted(
            r["by_site"].items(), key=lambda kv: -kv[1]["wire_bytes"]
        )
    ]
    out.append(_table(rows, ("call site", "calls", "wire")))
    out.append("")

    sh = r["shards"]
    if sh:
        rows = [
            (
                k,
                sh["mean_wall_s"][k],
                sh["max_wall_s"][k],
                sh["ratio_to_median"][k],
            )
            for k in range(len(sh["mean_wall_s"]))
        ]
        out.append(_table(
            rows,
            ("shard", "mean wall_s", "max wall_s", "ratio to median"),
        ))
        out.append(f"({sh['blocks_timed']} mesh blocks timed)")
        out.append("")
    if r["mesh_imbalance_warnings"]:
        rows = [
            (w.get("block"), w.get("shard"), w.get("value"),
             w.get("threshold"))
            for w in r["mesh_imbalance_warnings"]
        ]
        out.append(_table(
            rows, ("block", "straggler shard", "ratio", "threshold")
        ))
        out.append("")
    if r.get("lost_shards"):
        rows = [
            (w.get("block"), w.get("shard"), w.get("cause"),
             f"{w.get('shards_before')} -> {w.get('shards_after')}")
            for w in r["lost_shards"]
        ]
        out.append(_table(
            rows, ("block", "lost shard", "cause", "mesh"),
        ))
    return "\n".join(out).rstrip()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="JSONL trace file")
    ap.add_argument("--run", type=int, default=None,
                    help="run ordinal to report (default: last)")
    ap.add_argument("--all", action="store_true", help="report every run")
    ap.add_argument("--json", action="store_true",
                    help="print the rollup dict(s) as JSON instead")
    args = ap.parse_args(argv)

    # tolerate a torn final line: the trace may still be live
    events = read_trace(args.trace, strict=False)
    if not events:
        print(f"{args.trace}: no parseable events", file=sys.stderr)
        return 1
    runs = sorted({e.get("run", 0) for e in events})
    picked = runs if args.all else [
        args.run if args.run is not None else runs[-1]
    ]
    if args.json:
        out = [comms_rollup(events, r) for r in picked]
        print(json.dumps(out[0] if len(out) == 1 else out, indent=1))
        return 0
    chunks = [render_run(events, r) for r in picked]
    print(("\n\n" + "=" * 60 + "\n\n").join(chunks))
    return 0


if __name__ == "__main__":
    sys.exit(main())

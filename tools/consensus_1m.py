#!/usr/bin/env python
"""Config 2 at its PINNED scale: logistic N=1M data-sharded consensus
(BASELINE.json:8; VERDICT r3 missing #3).

Runs consensus ChEES over 8 shards of 1M rows with the dispatch-bounded
accelerator settings, quantifies the combine accuracy against a
full-data run at the same scale, and appends one row + the combine
error to BASELINE.md.  Run from tools/onchip.sh when the relay is
alive; falls through on CPU with an honest platform label (expect
~hours there — the 1M-row smoke is an on-chip measurement).

Usage: python tools/consensus_1m.py [--n 1000000] [--out BASELINE.md]
"""

import argparse
import datetime
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--out", default=None, metavar="BASELINE.md")
    ap.add_argument("--chains", type=int, default=8)
    ap.add_argument("--shards", type=int, default=8)
    args = ap.parse_args()

    from stark_tpu.platform import ensure_live_platform

    ensure_live_platform()

    import jax

    from stark_tpu.benchmarks import bench_consensus_logistic

    platform = jax.devices()[0].platform
    print(f"[consensus-1m] platform={platform} n={args.n}", file=sys.stderr)
    res = bench_consensus_logistic(
        n=args.n, num_shards=args.shards, chains=args.chains,
        combine_check=True,
    )
    err = res.extra.get("combine_rel_err")
    line = (
        f"| consensus_logistic N={args.n} | {res.ess_per_sec:.2f} | "
        f"{res.min_ess:.0f} | {res.wall_s:.1f} | {res.max_rhat:.3f} | "
        f"{'yes' if res.max_rhat < 1.01 else 'no'} | "
        f"combine_rel_err={err:.3f} | {platform} |"
    )
    print(res.row(), file=sys.stderr)
    print(line)
    if args.out:
        stamp = datetime.datetime.now().strftime("%Y-%m-%d %H:%M")
        with open(args.out, "a") as f:
            f.write(
                f"\n## Config 2 at pinned scale (N={args.n}, {stamp}, "
                f"platform={platform})\n\n"
                "combine_rel_err = max over coefficients of "
                "|mean_consensus - mean_full| / sd_full (posterior-sd "
                "units, full-data run at the same scale).\n\n"
                "| benchmark | ESS/s | min ESS | wall (s) | max R-hat | "
                "R-hat<1.01 | combine | platform |\n"
                "|---|---|---|---|---|---|---|---|\n"
                f"{line}\n"
            )
        print(f"appended to {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

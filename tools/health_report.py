#!/usr/bin/env python
"""Render the statistical-health trail of a telemetry trace.

    python tools/health_report.py /tmp/t.jsonl           # last run in file
    python tools/health_report.py /tmp/t.jsonl --run 1   # a specific run
    python tools/health_report.py /tmp/t.jsonl --all     # every run
    python tools/health_report.py /tmp/t.jsonl --json    # machine-readable

The sampler statistical-health observatory (``stark_tpu/health.py``)
emits schema'd ``health_warning`` events — the Stan-style taxonomy
(divergences / low_ebfmi / max_treedepth_saturation / low_accept /
stuck_chain / high_rhat / low_ess_per_param) with severity, measured
value vs its ``STARK_HEALTH_*`` threshold knob, affected chains, a
remediation hint, and (on ``divergences``) the bounded
divergence-snapshot ring that LOCALIZES where in parameter space the
sampler broke (a centered funnel's snapshots concentrate at low tau).
This tool renders that trail per run: a warning summary table, the
divergence-snapshot table, and the chain-health rollup
`telemetry.summarize_trace` already computes.

n/a-safe by contract: traces that predate PR 15 (or were written under
``STARK_HEALTH=0``) carry no ``health_warning`` events and render a
"no health events" line — never an error — so the tool is safe to point
at any trace the repo ever wrote.  Stdlib + the telemetry reader only
(no jax), so it runs anywhere the trace file lands.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List

# repo-root invocation without installation; tools/ for the shared
# table/format helpers (one renderer idiom across the report tools)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from stark_tpu.telemetry import read_trace, summarize_trace  # noqa: E402
from trace_report import _table  # noqa: E402

#: severity sort rank (most severe first in the table)
_SEV_RANK = {"error": 0, "warn": 1, "info": 2}


def health_summary(events: List[Dict[str, Any]], run: int) -> Dict[str, Any]:
    """Machine contract: one dict per run — the summarize_trace health
    rollup plus per-warning aggregates and the flattened snapshot ring."""
    s = summarize_trace(events, run=run)
    warns = [
        e for e in events
        if e.get("run", 0) == run and e.get("event") == "health_warning"
    ]
    by_name: Dict[str, Dict[str, Any]] = {}
    snapshots: List[Dict[str, Any]] = []
    for e in warns:
        name = str(e.get("warning", "unknown"))
        agg = by_name.setdefault(name, {
            "warning": name,
            "severity": e.get("severity"),
            "count": 0,
            "knob": e.get("knob"),
            "hint": e.get("hint"),
        })
        agg["count"] += 1
        for k in ("severity", "value", "threshold", "block", "problem_id",
                  "num_chains_affected"):
            if e.get(k) is not None:
                agg[k] = e[k]
        for snap in e.get("snapshots") or []:
            snapshots.append({
                "block": e.get("block"),
                **({"problem_id": e["problem_id"]}
                   if e.get("problem_id") is not None else {}),
                **snap,
            })
    return {
        "run": run,
        "health": s.get("health", {}),
        "warnings": sorted(
            by_name.values(),
            key=lambda w: (_SEV_RANK.get(str(w.get("severity")), 9),
                           w["warning"]),
        ),
        "snapshots": snapshots,
    }


def render_run(events: List[Dict[str, Any]], run: int) -> str:
    s = health_summary(events, run)
    out = [f"run {run}: statistical health"]
    h = s["health"]
    rollup = [
        ("max R-hat", h.get("max_rhat")),
        ("min ESS", h.get("min_ess")),
        ("divergences (cumulative, restart-chain)", h.get("num_divergent")),
        ("mean acceptance", h.get("mean_accept")),
        ("stuck components", h.get("num_stuck_components")),
        ("warnings emitted", h.get("warnings")),
    ]
    rows = [r for r in rollup if r[1] is not None]
    if rows:
        out.append("")
        out.append(_table(rows, ("chain health", "value")))
    if not s["warnings"]:
        out.append("")
        out.append(
            "(no health events — clean run at default thresholds, a "
            "pre-PR-15 trace, or STARK_HEALTH=0)"
        )
        return "\n".join(out)
    out.append("")
    out.append(_table(
        [
            (
                w["warning"],
                w.get("severity"),
                w["count"],
                w.get("value"),
                w.get("threshold"),
                w.get("knob"),
                w.get("problem_id"),
                w.get("hint"),
            )
            for w in s["warnings"]
        ],
        ("warning", "severity", "events", "last value", "threshold",
         "knob", "problem", "remediation"),
    ))
    if s["snapshots"]:
        out.append("")
        out.append("divergence localization (unconstrained coordinates, "
                   "first K per block):")
        rows = [
            (
                snap.get("block"),
                snap.get("problem_id"),
                snap.get("chain"),
                snap.get("step"),
                ", ".join(f"{float(v):.3g}" for v in snap.get("z", [])[:8]),
            )
            for snap in s["snapshots"]
        ]
        out.append(_table(
            rows, ("block", "problem", "chain", "step", "z[:8]")
        ))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="JSONL trace file")
    ap.add_argument("--run", type=int, default=None,
                    help="run ordinal to report (default: last)")
    ap.add_argument("--all", action="store_true", help="report every run")
    ap.add_argument("--json", action="store_true",
                    help="print the health summary dict(s) as JSON")
    args = ap.parse_args(argv)

    events = read_trace(args.trace, strict=False)
    if not events:
        print(f"{args.trace}: no parseable events", file=sys.stderr)
        return 1
    runs = sorted({e.get("run", 0) for e in events})
    picked = (
        runs if args.all
        else [args.run if args.run is not None else runs[-1]]
    )
    if args.json:
        out = [health_summary(events, r) for r in picked]
        print(json.dumps(out[0] if len(out) == 1 else out, indent=1))
        return 0
    chunks = [render_run(events, r) for r in picked]
    print(("\n\n" + "=" * 60 + "\n\n").join(chunks))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Reconstruct a tenant's end-to-end story from the lineage trail.

    python tools/lineage_report.py /tmp/t.jsonl                  # fleet rollup
    python tools/lineage_report.py /tmp/t.jsonl --job j-ab12...  # one tenant
    python tools/lineage_report.py /tmp/t.jsonl --problem glm-3  # by tenant id
    python tools/lineage_report.py /tmp/t.jsonl --fleet          # force rollup
    python tools/lineage_report.py /tmp/t.jsonl --postmortem wd  # + pm bundles
    python tools/lineage_report.py /tmp/t.jsonl --json           # machine form

The tenant lineage observatory (``stark_tpu/lineage.py``) stamps one
stable ``job_id`` onto every tenant-scoped event from ``feed_submit``
through sampling, incidents (shard loss, reseed, quarantine,
health warnings), ``problem_converged``, and — via the summary
sidecar, across a process boundary — every ``/posterior/<id>/*``
``serve_request``.  This tool replays that trail as a human timeline:

    submit -> admitted/placed -> warm-start -> blocks (with SLO burn)
           -> incidents -> converged -> first/Nth serve

Inputs are whatever the run left behind, folded together: one or more
trace files (rotated ``<trace>.N`` predecessors are discovered
automatically), the atomic ``<trace>.lineage.json`` index sidecar
(``--index``; used for the rollup when present so multi-GB traces are
not rescanned — the timeline still reads the raw events), and
flight-recorder postmortem bundles (``--postmortem <workdir>`` scans
``postmortem/pm*/events.jsonl``).  Every record set also yields a
``coverage`` fraction — the share of job-bearing event types that
actually carry a ``job_id`` — the number the lineage E2E drill asserts
is >= 0.95.

n/a-safe by contract: a pre-lineage trace (or one written under
``STARK_LINEAGE=0``) has no job ids and renders "no lineage evidence",
never an error.  Stdlib + the telemetry reader only (no jax), so it
runs anywhere the trace lands.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

# repo-root invocation without installation; tools/ for the shared
# table/format helpers (one renderer idiom across the report tools)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from stark_tpu import lineage, telemetry  # noqa: E402
from trace_report import _table  # noqa: E402

#: human labels for the timeline, in the order a lifecycle unfolds
_TIMELINE_LABELS = {
    "feed_submit": "submitted to feed",
    "feed_reject": "REJECTED at admission",
    "problem_admitted": "admitted / placed in slot",
    "slot_recycled": "slot recycled",
    "problem_reseeded": "RESEED (restart)",
    "problem_quarantined": "QUARANTINED",
    "problem_converged": "converged",
    "shard_lost": "SHARD LOST (re-homed)",
    "checkpoint": "checkpoint",
    "health_warning": "health warning",
    "serve_request": "served",
    "slo_burn": "slo burn",
    "fault": "fault",
}

#: block-cadence event types collapsed into one "sampled N blocks" line
#: per contiguous stretch (a 10k-block run should not print 10k rows)
_BLOCK_EVENTS = ("warmup_block", "sample_block")


# --------------------------------------------------------------------------
# gathering evidence
# --------------------------------------------------------------------------


def gather_events(
    traces: List[str], postmortem: Optional[str]
) -> List[Dict[str, Any]]:
    """All parseable records from the trace files (rotated predecessors
    included, oldest first) plus any flight-recorder bundles."""
    events: List[Dict[str, Any]] = []
    for path in traces:
        for part in telemetry.rotated_paths(path):
            try:
                events.extend(telemetry.iter_trace(part, strict=False))
            except OSError:
                continue
    if postmortem:
        pat = os.path.join(postmortem, "postmortem", "pm*", "events.jsonl")
        for bundle in sorted(glob.glob(pat)):
            try:
                events.extend(telemetry.iter_trace(bundle, strict=False))
            except OSError:
                continue
    return events


def load_index(
    traces: List[str], explicit: Optional[str],
    events: List[Dict[str, Any]],
) -> Tuple[lineage.LineageIndex, str]:
    """The per-job rollups.

    Folded fresh from the gathered events (the raw trail is the source
    of truth, and the timeline needs a full read anyway); the
    ``<trace>.lineage.json`` sidecar then contributes any job it knows
    that the events no longer show — a tenant whose records were
    rotated into a file that got pruned.  Folding the sidecar's OWN
    counts on top of the events would double-count, so overlap always
    resolves to the fresh fold."""
    idx = lineage.LineageIndex().fold_events(events)
    src = "(folded from events)"
    candidates = (
        [explicit] if explicit
        else [lineage.index_path(p) for p in traces]
    )
    for path in candidates:
        if path and os.path.exists(path):
            side = lineage.LineageIndex.load(path)
            if side is None:
                continue
            src = f"events + {path}"
            for rec in side.jobs():
                if idx.job(rec["job_id"]) is None:
                    idx.adopt(rec)
            break
    return idx, src


def coverage(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The drill's acceptance number: of all job-bearing-TYPE events
    that reference a tenant at all, what fraction carry a job id.

    An event type being in `lineage.JOB_EVENT_TYPES` says the family is
    tenant-correlat*able* — individual instances may still be
    fleet-global (a batch-level ``warmup_block`` phase, a
    ``stage="fleet"`` checkpoint) and name no tenant.  Those carry
    nothing to correlate, so they sit outside both numerator and
    denominator; counting them would make the coverage number report
    the fleet's emission style, not lineage's stamping fidelity."""
    bearing = carrying = 0
    missing: Dict[str, int] = {}
    for e in events:
        ev = e.get("event")
        if ev not in lineage.JOB_EVENT_TYPES:
            continue
        if not any(
            k in e
            for k in ("problem_id", "problem_ids", "to_problem",
                      "job_id", "job_ids")
        ):
            continue
        bearing += 1
        if e.get("job_id") is not None or e.get("job_ids") is not None:
            carrying += 1
        else:
            missing[ev] = missing.get(ev, 0) + 1
    return {
        "job_bearing_events": bearing,
        "carrying_job_id": carrying,
        "fraction": round(carrying / bearing, 4) if bearing else None,
        "missing_by_event": missing,
    }


# --------------------------------------------------------------------------
# one tenant's timeline
# --------------------------------------------------------------------------


def _matches(e: Dict[str, Any], job_id: str) -> bool:
    if e.get("job_id") == job_id:
        return True
    jids = e.get("job_ids")
    return isinstance(jids, (list, tuple)) and job_id in jids


def job_timeline(
    events: List[Dict[str, Any]], job_id: str
) -> List[Dict[str, Any]]:
    """The tenant's story as ordered entries; contiguous block-cadence
    stretches collapse into one summary entry each."""
    mine = [e for e in events if _matches(e, job_id)]
    mine.sort(key=lambda e: (e.get("ts") or 0.0))
    out: List[Dict[str, Any]] = []
    run: List[Dict[str, Any]] = []  # current block-event stretch

    def flush():
        if not run:
            return
        first, last = run[0], run[-1]
        out.append({
            "ts": first.get("ts"),
            "what": "sampling",
            "detail": (
                f"{len(run)} block events "
                f"(block {first.get('block')}..{last.get('block')})"
            ),
        })
        run.clear()

    for e in mine:
        ev = e.get("event")
        if ev in _BLOCK_EVENTS:
            run.append(e)
            continue
        flush()
        entry: Dict[str, Any] = {
            "ts": e.get("ts"),
            "what": _TIMELINE_LABELS.get(ev, ev),
        }
        detail = []
        if ev == "feed_submit":
            detail.append(f"depth={e.get('depth')}")
            if e.get("budgeted"):
                detail.append("budgeted")
        elif ev == "problem_admitted":
            if e.get("slot") is not None:
                detail.append(f"slot={e.get('slot')}")
            if e.get("donor") is not None:
                detail.append(f"warm-start from donor {e.get('donor')}")
        elif ev == "slo_burn":
            detail.extend(
                f"{k.replace('_burn', '')}={e[k]:.0%}"
                for k in ("deadline_burn", "restart_burn", "ess_burn")
                if isinstance(e.get(k), (int, float))
            )
        elif ev == "health_warning":
            detail.append(str(e.get("warning")))
            if e.get("value") is not None:
                detail.append(f"value={e.get('value')}")
        elif ev == "shard_lost":
            detail.append(f"shards={e.get('lost_shards', e.get('shard'))}")
        elif ev == "problem_converged":
            detail.append(f"status={e.get('status')}")
            if e.get("blocks") is not None:
                detail.append(f"blocks={e.get('blocks')}")
        elif ev == "serve_request":
            detail.append(f"endpoint={e.get('endpoint')}")
            if e.get("cache") is not None:
                detail.append(f"cache={e.get('cache')}")
        elif ev == "checkpoint":
            if e.get("block") is not None:
                detail.append(f"block={e.get('block')}")
        if e.get("problem_id") is not None and ev in (
            "feed_submit", "problem_admitted",
        ):
            detail.insert(0, f"problem={e.get('problem_id')}")
        entry["detail"] = ", ".join(str(d) for d in detail)
        out.append(entry)
    flush()
    return out


def resolve_job(
    idx: lineage.LineageIndex, job: Optional[str], problem: Optional[str]
) -> Optional[str]:
    """--job wins; --problem maps a tenant id to its job via the index."""
    if job:
        return job
    if problem:
        for rec in idx.jobs():
            if rec.get("problem_id") == problem:
                return rec["job_id"]
    return None


# --------------------------------------------------------------------------
# rendering
# --------------------------------------------------------------------------


def render_fleet(idx: lineage.LineageIndex, cov: Dict[str, Any]) -> str:
    jobs = idx.jobs()
    out = [f"tenant lineage: {len(jobs)} job(s)"]
    if not jobs:
        out.append("(no lineage evidence — pre-lineage trace or "
                   "STARK_LINEAGE=0)")
        return "\n".join(out)
    rows = []
    for r in jobs:
        serves = r.get("serves") or {}
        rows.append((
            r["job_id"],
            r.get("problem_id"),
            r.get("state"),
            r.get("blocks"),
            r.get("restarts"),
            r.get("shard_losses"),
            r.get("health_warnings"),
            sum(v for v in serves.values() if isinstance(v, int)),
            (f"{r['duration_s']:.1f}s"
             if isinstance(r.get("duration_s"), (int, float)) else None),
        ))
    out.append("")
    out.append(_table(
        rows,
        ("job", "problem", "state", "blocks", "restarts", "shard_loss",
         "warnings", "serves", "span"),
    ))
    if cov["fraction"] is not None:
        out.append("")
        out.append(
            f"job_id coverage: {cov['carrying_job_id']}/"
            f"{cov['job_bearing_events']} job-bearing events "
            f"({cov['fraction']:.1%})"
        )
    return "\n".join(out)


def render_job(
    job_id: str, rec: Optional[Dict[str, Any]],
    timeline: List[Dict[str, Any]],
) -> str:
    out = [f"job {job_id}"]
    if rec:
        head = [
            ("problem", rec.get("problem_id")),
            ("state", rec.get("state")),
            ("status", rec.get("status")),
            ("blocks", rec.get("blocks")),
            ("restarts", rec.get("restarts")),
            ("shard losses", rec.get("shard_losses")),
            ("checkpoints", rec.get("checkpoints")),
            ("health warnings", rec.get("health_warnings")),
            ("serves", rec.get("serves")),
            ("span", rec.get("duration_s")),
        ]
        out.append("")
        out.append(_table(
            [(k, v) for k, v in head if v is not None], ("field", "value")
        ))
    if not timeline:
        out.append("")
        out.append("(no events carry this job id)")
        return "\n".join(out)
    t0 = next(
        (e["ts"] for e in timeline if isinstance(e.get("ts"), (int, float))),
        None,
    )
    rows = []
    for e in timeline:
        ts = e.get("ts")
        rel = (
            f"+{ts - t0:.2f}s"
            if isinstance(ts, (int, float)) and t0 is not None else ""
        )
        rows.append((rel, e["what"], e.get("detail") or ""))
    out.append("")
    out.append(_table(rows, ("t", "milestone", "detail")))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("traces", nargs="+",
                    help="JSONL trace file(s); rotated <trace>.N "
                         "predecessors are folded in automatically")
    ap.add_argument("--job", default=None,
                    help="report one tenant by job id")
    ap.add_argument("--problem", default=None,
                    help="report one tenant by problem id")
    ap.add_argument("--fleet", action="store_true",
                    help="force the fleet rollup table (the default "
                         "when no tenant is selected)")
    ap.add_argument("--index", default=None,
                    help="lineage index sidecar (default: "
                         "<trace>.lineage.json when present)")
    ap.add_argument("--postmortem", default=None,
                    help="workdir whose postmortem/pm*/events.jsonl "
                         "bundles should be folded in")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report")
    args = ap.parse_args(argv)

    events = gather_events(args.traces, args.postmortem)
    if not events:
        print(f"{args.traces[0]}: no parseable events", file=sys.stderr)
        return 1
    idx, idx_src = load_index(args.traces, args.index, events)
    cov = coverage(events)

    job_id = resolve_job(idx, args.job, args.problem)
    if (args.job or args.problem) and (
        job_id is None or idx.job(job_id) is None
    ):
        sel = args.job or args.problem
        print(f"no lineage record matches {sel!r}", file=sys.stderr)
        return 1

    if args.json:
        payload: Dict[str, Any] = {
            "schema": lineage.INDEX_SCHEMA,
            "index_source": idx_src,
            "coverage": cov,
            "jobs": idx.jobs(),
        }
        if job_id is not None and not args.fleet:
            payload["job"] = idx.job(job_id)
            payload["timeline"] = job_timeline(events, job_id)
        print(json.dumps(payload, indent=1, default=str))
        return 0

    if job_id is not None and not args.fleet:
        print(render_job(job_id, idx.job(job_id),
                         job_timeline(events, job_id)))
        if cov["fraction"] is not None:
            print(f"\njob_id coverage (whole trace): {cov['fraction']:.1%}")
    else:
        print(render_fleet(idx, cov))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Repo lint: raw collectives live ONLY in the parallel primitives layer.

PR 16's communication observatory accounts every collective dispatch
(bytes, participants, host-blocked wall) by instrumenting ONE choke
point: ``stark_tpu/parallel/primitives.py``.  That accounting is only
trustworthy while the choke point is actually unique — a raw
``lax.psum`` / ``lax.all_gather`` / ``process_allgather`` /
``shard_map`` call anywhere else moves bytes the observatory never
sees, silently re-opening the blind spot the layer exists to close.
This lint pins the invariant statically (mirroring
``tools/lint_failpoints.py``):

1. AST-collect every call to a raw-collective name under ``stark_tpu/``.
2. Fail on any call outside the allowed homes —
   ``stark_tpu/parallel/primitives.py`` (the accounting layer itself)
   and ``stark_tpu/compat.py`` (version-shim lookups, not dispatches).

``lax.pmean`` / ``lax.pmax`` stay un-linted by design: they are
in-kernel reductions over the chains axis whose traffic rides the same
fused program as the accounted ``psum`` — adding them to the wall would
double-count without adding information.  AST-based, so collective
names in comments/docstrings can't trip it; imports nothing from the
package, so it runs anywhere.  Run directly or via
``tests/test_lint_collectives.py`` (tier-1).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Tuple

#: raw-collective call names the primitives layer must monopolize
_COLLECTIVE_FUNCS = frozenset({
    "psum", "all_gather", "process_allgather", "shard_map",
})

#: repo-relative files allowed to touch raw collectives: the accounting
#: layer itself, and the version shim that only RESOLVES the symbols
_ALLOWED = frozenset({
    os.path.join("stark_tpu", "parallel", "primitives.py"),
    os.path.join("stark_tpu", "compat.py"),
})


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def find_collective_calls(
    source: str, filename: str
) -> List[Tuple[int, str]]:
    """(lineno, name) for every raw-collective call in a module."""
    tree = ast.parse(source, filename=filename)
    hits = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and _call_name(node) in _COLLECTIVE_FUNCS
        ):
            hits.append((node.lineno, _call_name(node)))
    return hits


def collect_calls(repo: str) -> Dict[str, List[Tuple[int, str]]]:
    """repo-relative path -> [(line, collective), ...] under stark_tpu/."""
    calls: Dict[str, List[Tuple[int, str]]] = {}
    pkg_dir = os.path.join(repo, "stark_tpu")
    for root, _dirs, files in os.walk(pkg_dir):
        if "__pycache__" in root:
            continue
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path) as f:
                source = f.read()
            hits = find_collective_calls(source, path)
            if hits:
                calls[os.path.relpath(path, repo)] = hits
    return calls


def lint_repo(repo: str) -> List[str]:
    """Violation strings for the whole repo; empty = clean."""
    calls = collect_calls(repo)
    if not any(rel in _ALLOWED for rel in calls):
        return ["no raw collective calls found in the allowed homes "
                "(stark_tpu/parallel/primitives.py) — the collector "
                "itself is broken"]
    violations = []
    for rel in sorted(calls):
        if rel in _ALLOWED:
            continue
        for lineno, name in calls[rel]:
            violations.append(
                f"{os.path.join(repo, rel)}:{lineno}: raw collective "
                f"{name!r} outside the parallel primitives layer — "
                "route it through stark_tpu.parallel.primitives "
                "(reduce_tree/gather_axis/broadcast/shard_put/"
                "gather_tree) so the comms observatory accounts it"
            )
    return violations


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = lint_repo(repo)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} raw-collective violation(s) — see "
            "tools/lint_collectives.py docstring",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

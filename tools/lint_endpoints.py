#!/usr/bin/env python
"""Repo lint: every statusd route must be documented AND contract-tested.

``stark_tpu/statusd.py`` declares its endpoint contract in the
``ROUTES`` tuple — the exact paths the daemon serves (``/metrics``,
``/healthz``, ``/status``, and the ``/posterior/<id>/*`` read plane).
Operators curl these and dashboards scrape them, so an endpoint that
exists only in handler code is the same documentation gap a registered-
but-undocumented metric is (``lint_metrics_docs.py``) or an undocumented
env knob (``lint_fused_knobs.py``).  This lint closes it for routes, in
both directions a route can go stale:

* **README** — every ``ROUTES`` entry must appear in a markdown TABLE
  row of ``README.md`` (the endpoint table; prose or curl examples
  don't count, same rule as the metric lint).
* **tests/** — every ``ROUTES`` entry must appear as a literal in at
  least one ``tests/*.py`` file, so each endpoint has a named contract
  test and deleting or renaming a route breaks a test, not a dashboard.

The ``ROUTES`` tuple is read by AST from the source file (no import of
``stark_tpu.statusd``, so the lint runs without jax or a network
stack).  Run directly (``python tools/lint_endpoints.py``) or via the
test suite (``tests/test_lint_endpoints.py``).
"""

from __future__ import annotations

import ast
import glob
import os
import sys
from typing import List


def find_routes(source: str, filename: str) -> List[str]:
    """The string elements of the module-level ``ROUTES`` assignment."""
    tree = ast.parse(source, filename=filename)
    for node in tree.body:
        targets = (
            node.targets if isinstance(node, ast.Assign)
            else [node.target] if isinstance(node, ast.AnnAssign) else []
        )
        if not any(
            isinstance(t, ast.Name) and t.id == "ROUTES" for t in targets
        ):
            continue
        value = node.value
        if not isinstance(value, (ast.Tuple, ast.List)):
            return []
        return [
            el.value
            for el in value.elts
            if isinstance(el, ast.Constant) and isinstance(el.value, str)
        ]
    return []


def lint_repo(repo: str) -> List[str]:
    """Violation strings for the whole repo; empty = clean."""
    statusd_path = os.path.join(repo, "stark_tpu", "statusd.py")
    with open(statusd_path) as f:
        routes = find_routes(f.read(), statusd_path)
    if not routes:
        return [
            "no ROUTES tuple found in stark_tpu/statusd.py — the "
            "endpoint contract declaration is missing"
        ]
    readme_path = os.path.join(repo, "README.md")
    readme = open(readme_path).read() if os.path.exists(readme_path) else ""
    # the contract is the endpoint TABLE, not any prose mention (the
    # lint_metrics_docs rule): restrict the search to table rows
    table_rows = "\n".join(
        line for line in readme.splitlines() if line.lstrip().startswith("|")
    )
    tests_src = "".join(
        open(p).read()
        for p in sorted(glob.glob(os.path.join(repo, "tests", "*.py")))
    )
    violations = []
    for route in routes:
        if route not in table_rows:
            violations.append(
                f"{statusd_path}: route {route!r} is served but missing "
                "from the README endpoint table — document it (a table "
                "row; prose or curl examples don't count)"
            )
        if route not in tests_src:
            violations.append(
                f"{statusd_path}: route {route!r} has no contract test — "
                "name it as a literal in a tests/*.py file so renaming "
                "or deleting the endpoint breaks a test, not a dashboard"
            )
    return violations


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = lint_repo(repo)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} endpoint contract gap(s) — see "
            "tools/lint_endpoints.py docstring",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Repo lint: every failpoint site must be exercised by a drill or test.

The fault-injection harness (`stark_tpu/faults.py`) only earns its keep
when every *named site* compiled into the hot paths is actually fired by
something — a chaos scenario or a test.  A site nothing exercises is a
recovery path nobody has ever watched recover: the next refactor can
break the containment behind it silently.  This lint closes the loop
statically (mirroring ``tools/lint_fused_knobs.py``):

1. AST-collect every site name passed as a string literal to a faults
   call (``fail_point`` / ``poison`` / ``corrupt_file`` /
   ``kill_shards``) under ``stark_tpu/``.
2. Fail if a collected site is armed/fired by NO string literal inside
   an arming call (``faults.configure`` / ``enable`` / a direct site
   call / a ``STARK_FAILPOINTS`` ``setenv``) in ``stark_tpu/chaos.py``
   (the scripted drill matrix) or under ``tests/`` — every site needs
   at least one scenario or test that arms it by name.

AST-based ON BOTH SIDES: site names in comments/docstrings neither trip
the collector nor satisfy the exercise check (a deleted drill whose site
name survives in prose must still fail the lint).  Imports nothing from
the package, so it runs anywhere.  Run directly or via
``tests/test_lint_failpoints.py``.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

#: call names whose string-literal first argument is a failpoint site
#: (the full faults.py site API: the control-flow entry plus the three
#: data-directive helpers, each of which routes through fail_point)
_SITE_FUNCS = frozenset({
    "fail_point", "poison", "corrupt_file", "kill_shards",
})

#: call names whose string-literal arguments ARM sites in drills/tests —
#: configure/enable take the ``site=action`` grammar, the site calls arm
#: implicitly, and setenv covers STARK_FAILPOINTS-driven subprocles
_ARM_FUNCS = _SITE_FUNCS | frozenset({"configure", "enable", "setenv"})


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def find_site_calls(source: str, filename: str) -> List[Tuple[int, str]]:
    """(lineno, site) for every literal-site faults call in a module."""
    tree = ast.parse(source, filename=filename)
    hits = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and _call_name(node) in _SITE_FUNCS
            and node.args
        ):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            hits.append((node.lineno, arg.value))
    return hits


def collect_sites(pkg_dir: str) -> Dict[str, List[str]]:
    """site -> ["path:line", ...] across the package (faults.py itself
    defines the helpers and passes the site through a variable, so it
    contributes no literals — by construction, not by exclusion)."""
    sites: Dict[str, List[str]] = {}
    for root, _dirs, files in os.walk(pkg_dir):
        if "__pycache__" in root:
            continue
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path) as f:
                source = f.read()
            for lineno, site in find_site_calls(source, path):
                sites.setdefault(site, []).append(f"{path}:{lineno}")
    return sites


def _arming_literals(source: str, filename: str) -> List[str]:
    """Every string literal passed to an arming call — the text a site
    name must appear in (as the site itself or inside a
    ``site=action`` / env grammar string) to count as exercised."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError:
        return []
    lits = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call) and _call_name(node) in _ARM_FUNCS
        ):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                lits.append(arg.value)
    return lits


def _exercised_sites(paths: List[str], needles: Set[str]) -> Set[str]:
    """Which sites appear inside an arming-call string literal in any of
    the given .py files/trees."""
    found: Set[str] = set()
    for p in paths:
        if os.path.isfile(p):
            files = [p]
        else:
            files = [
                os.path.join(root, name)
                for root, _dirs, names in os.walk(p)
                if "__pycache__" not in root
                for name in names
                if name.endswith(".py")
            ]
        for f in files:
            with open(f) as fh:
                source = fh.read()
            for lit in _arming_literals(source, f):
                found.update(n for n in needles if _site_in_literal(n, lit))
            if found == needles:
                return found
    return found


def _site_in_literal(site: str, lit: str) -> bool:
    """True iff ``lit`` arms ``site`` — either the bare site name (a
    direct site call) or ``site=action`` at a grammar boundary.  Bare
    substring containment would let a site named as a PREFIX of another
    armed site (``fleet.lane`` vs ``fleet.lane_nan=...``) pass with
    zero coverage."""
    if lit == site:
        return True
    return re.search(
        rf"(^|[;,\s]){re.escape(site)}\s*=", lit
    ) is not None


def lint_repo(repo: str) -> List[str]:
    """Violation strings for the whole repo; empty = clean."""
    sites = collect_sites(os.path.join(repo, "stark_tpu"))
    if not sites:
        return ["no literal failpoint sites found under stark_tpu/ — "
                "the collector itself is broken"]
    exercised = _exercised_sites(
        [os.path.join(repo, "stark_tpu", "chaos.py"),
         os.path.join(repo, "tests")],
        set(sites),
    )
    violations = []
    for site in sorted(sites):
        if site not in exercised:
            violations.append(
                f"{sites[site][0]}: failpoint site {site!r} is exercised "
                "by no chaos scenario (stark_tpu/chaos.py) and no test "
                "under tests/ — an undrilled recovery path; add a "
                "scenario or test that arms it by name"
            )
    return violations


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = lint_repo(repo)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} failpoint-coverage violation(s) — see "
            "tools/lint_failpoints.py docstring",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Repo lint: every kernel-execution env knob must be documented + tested.

The fused-op layer grew a family of env knobs (the shared precision pair
plus one boolean per likelihood family), the kernel scheduler added
``STARK_RAGGED_NUTS``, and the quantized data-plane added the
``STARK_QUANT_*`` calibration knobs (ops/quantize.py) — each changes
which executable evaluates every gradient (or how the batched loops
schedule them, or what bytes the packed design matrix holds) for a run.
An undocumented knob is invisible to operators; an untested one can
silently lose its fallback path.  This lint closes both loops
statically:

1. AST-collect every covered knob string literal (``STARK_FUSED_<NAME>``,
   ``STARK_RAGGED_NUTS``, ``STARK_QUANT_<NAME>``, or the fleet
   slot-scheduler pair ``STARK_FLEET_SLOTS`` / ``STARK_FLEET_WARMSTART``)
   passed to an env-read call (``os.environ.get`` / ``os.getenv`` /
   ``environ.pop`` / ``precision.fused_knob``) under ``stark_tpu/``.
2. Fail if a collected knob is missing from the README (the
   operator-facing contract — the zoo-coverage table for fused knobs,
   the "Ragged NUTS scheduling" section for the scheduler knob), or
3. appears nowhere under ``tests/`` (every knob needs a test exercising
   its fallback / knob-off bit-identity behavior by name).

AST-based (strings in comments can't trip it); imports nothing from the
package, so it runs anywhere.  Run directly or via
``tests/test_lint_fused_knobs.py``.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

#: call names whose string-literal argument is an env-knob read
_READ_FUNCS = frozenset({"get", "getenv", "pop", "fused_knob"})

#: covered knobs: the fused-op family, the kernel-scheduler knob, the
#: quant-calibration family, the fleet slot-scheduler pair
#: (STARK_FLEET_SLOTS pins the compiled batch shape, STARK_FLEET_WARMSTART
#: turns on donor-seeded admission warmup — each changes which executable
#: / how much warmup every admitted problem runs), and the
#: device-parallel fleet knob (STARK_FLEET_MESH shards the problem axis
#: over a mesh — a different compiled dispatch per shard), and the
#: comms-observatory switch (STARK_COMM_TELEMETRY=0 silences collective
#: accounting for byte-identical traces), and the elastic-fault-domain
#: pair (STARK_SHARD_DEADLINE arms the mesh fleet's shard deadman —
#: detection + degraded re-shard change the dispatch path;
#: STARK_FEED_MAXDEPTH bounds FleetFeed admission, changing what
#: `submit` does under load), and the posterior-serving read-plane
#: family (STARK_SERVE_* — serving.py's LRU capacity / telemetry switch
#: / sketch + predict caps, plus statusd's STARK_SERVE_ROOT auto-attach:
#: each changes what a read request serves or emits) — extend the
#: alternation when a new execution-path knob family lands
_KNOB_RE = re.compile(
    r"^STARK_(?:FUSED_[A-Z0-9_]+|RAGGED_NUTS|QUANT_[A-Z0-9_]+"
    r"|FLEET_SLOTS|FLEET_WARMSTART|FLEET_MESH|COMM_TELEMETRY"
    r"|SHARD_DEADLINE|FEED_MAXDEPTH|SERVE_[A-Z0-9_]+)$"
)


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def find_knob_reads(source: str, filename: str) -> List[Tuple[int, str]]:
    """(lineno, knob) for every STARK_FUSED_* literal in an env-read call."""
    tree = ast.parse(source, filename=filename)
    hits = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _call_name(node) in _READ_FUNCS):
            continue
        for arg in node.args:
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and _KNOB_RE.match(arg.value)
            ):
                hits.append((node.lineno, arg.value))
    return hits


def collect_knobs(pkg_dir: str) -> Dict[str, List[str]]:
    """knob -> ["path:line", ...] across the package."""
    knobs: Dict[str, List[str]] = {}
    for root, _dirs, files in os.walk(pkg_dir):
        if "__pycache__" in root:
            continue
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path) as f:
                source = f.read()
            for lineno, knob in find_knob_reads(source, path):
                knobs.setdefault(knob, []).append(f"{path}:{lineno}")
    return knobs


def _grep_tree(tree_dir: str, needles: Set[str]) -> Set[str]:
    """Which needles appear in any .py file under tree_dir."""
    found: Set[str] = set()
    for root, _dirs, files in os.walk(tree_dir):
        if "__pycache__" in root:
            continue
        for name in files:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(root, name)) as f:
                text = f.read()
            found.update(n for n in needles if n in text)
            if found == needles:
                return found
    return found


def lint_repo(repo: str) -> List[str]:
    """Violation strings for the whole repo; empty = clean."""
    knobs = collect_knobs(os.path.join(repo, "stark_tpu"))
    if not knobs:
        return ["no STARK_FUSED_*/STARK_RAGGED_NUTS/STARK_QUANT_* env "
                "reads found under stark_tpu/ — the collector itself is "
                "broken"]
    violations = []
    readme_path = os.path.join(repo, "README.md")
    readme = open(readme_path).read() if os.path.exists(readme_path) else ""
    tested = _grep_tree(os.path.join(repo, "tests"), set(knobs))
    for knob in sorted(knobs):
        where = knobs[knob][0]
        if knob not in readme:
            violations.append(
                f"{where}: {knob} is read but missing from the README "
                "coverage docs — document the knob (zoo table for fused "
                "knobs; Performance section for scheduler knobs)"
            )
        if knob not in tested:
            violations.append(
                f"{where}: {knob} is read but referenced by no test under "
                "tests/ — add a fallback / knob-off bit-identity test "
                "that names the knob"
            )
    return violations


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = lint_repo(repo)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} STARK_FUSED_* knob violation(s) — see "
            "tools/lint_fused_knobs.py docstring",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Repo lint: every ``STARK_FUSED_*`` knob must be documented and tested.

The fused-op layer grew a family of env knobs (the shared precision pair
plus one boolean per likelihood family), each changing which executable
evaluates every gradient of a run.  An undocumented knob is invisible to
operators; an untested one can silently lose its autodiff fallback.
This lint closes both loops statically:

1. AST-collect every ``STARK_FUSED_<NAME>`` string literal passed to an
   env-read call (``os.environ.get`` / ``os.getenv`` / ``environ.pop`` /
   ``precision.fused_knob``) under ``stark_tpu/``.
2. Fail if a collected knob is missing from the README zoo-coverage
   table (the operator-facing contract), or
3. appears nowhere under ``tests/`` (every knob needs a test exercising
   its fallback/retrace behavior — the per-op knob-off bit-identity and
   precision-retrace tests reference the knob by name).

AST-based (strings in comments can't trip it); imports nothing from the
package, so it runs anywhere.  Run directly or via
``tests/test_lint_fused_knobs.py``.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

#: call names whose string-literal argument is an env-knob read
_READ_FUNCS = frozenset({"get", "getenv", "pop", "fused_knob"})

_KNOB_RE = re.compile(r"^STARK_FUSED_[A-Z0-9_]+$")


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def find_knob_reads(source: str, filename: str) -> List[Tuple[int, str]]:
    """(lineno, knob) for every STARK_FUSED_* literal in an env-read call."""
    tree = ast.parse(source, filename=filename)
    hits = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _call_name(node) in _READ_FUNCS):
            continue
        for arg in node.args:
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and _KNOB_RE.match(arg.value)
            ):
                hits.append((node.lineno, arg.value))
    return hits


def collect_knobs(pkg_dir: str) -> Dict[str, List[str]]:
    """knob -> ["path:line", ...] across the package."""
    knobs: Dict[str, List[str]] = {}
    for root, _dirs, files in os.walk(pkg_dir):
        if "__pycache__" in root:
            continue
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path) as f:
                source = f.read()
            for lineno, knob in find_knob_reads(source, path):
                knobs.setdefault(knob, []).append(f"{path}:{lineno}")
    return knobs


def _grep_tree(tree_dir: str, needles: Set[str]) -> Set[str]:
    """Which needles appear in any .py file under tree_dir."""
    found: Set[str] = set()
    for root, _dirs, files in os.walk(tree_dir):
        if "__pycache__" in root:
            continue
        for name in files:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(root, name)) as f:
                text = f.read()
            found.update(n for n in needles if n in text)
            if found == needles:
                return found
    return found


def lint_repo(repo: str) -> List[str]:
    """Violation strings for the whole repo; empty = clean."""
    knobs = collect_knobs(os.path.join(repo, "stark_tpu"))
    if not knobs:
        return ["no STARK_FUSED_* env reads found under stark_tpu/ — "
                "the collector itself is broken"]
    violations = []
    readme_path = os.path.join(repo, "README.md")
    readme = open(readme_path).read() if os.path.exists(readme_path) else ""
    tested = _grep_tree(os.path.join(repo, "tests"), set(knobs))
    for knob in sorted(knobs):
        where = knobs[knob][0]
        if knob not in readme:
            violations.append(
                f"{where}: {knob} is read but missing from the README "
                "zoo-coverage table — document the knob (model, default, "
                "parity band)"
            )
        if knob not in tested:
            violations.append(
                f"{where}: {knob} is read but referenced by no test under "
                "tests/ — add an autodiff-fallback / retrace test that "
                "names the knob"
            )
    return violations


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = lint_repo(repo)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} STARK_FUSED_* knob violation(s) — see "
            "tools/lint_fused_knobs.py docstring",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Repo lint: every kernel-execution env knob must be documented + tested.

The fused-op layer grew a family of env knobs (the shared precision pair
plus one boolean per likelihood family), the kernel scheduler added
``STARK_RAGGED_NUTS``, and the quantized data-plane added the
``STARK_QUANT_*`` calibration knobs (ops/quantize.py) — each changes
which executable evaluates every gradient (or how the batched loops
schedule them, or what bytes the packed design matrix holds) for a run.
An undocumented knob is invisible to operators; an untested one can
silently lose its fallback path.  This lint closes both loops
statically:

1. AST-collect every covered knob string literal (``STARK_FUSED_<NAME>``,
   ``STARK_RAGGED_NUTS``, ``STARK_QUANT_<NAME>``, or the fleet
   slot-scheduler pair ``STARK_FLEET_SLOTS`` / ``STARK_FLEET_WARMSTART``)
   passed to an env-read call (``os.environ.get`` / ``os.getenv`` /
   ``environ.pop`` / ``precision.fused_knob``) under ``stark_tpu/``.
2. Fail if a collected knob is missing from the README (the
   operator-facing contract — the zoo-coverage table for fused knobs,
   the "Ragged NUTS scheduling" section for the scheduler knob), or
3. appears nowhere under ``tests/`` (every knob needs a test exercising
   its fallback / knob-off bit-identity behavior by name).
4. Registry completeness for the autotuner (``STARK_PROFILE*`` family,
   stark_tpu/profile.py): every TUNABLE knob (fused family + dtype +
   precision, ragged scheduler, quant percentile, fleet trio) must
   appear in ``profile.CANDIDATE_SPACE`` — a knob outside the candidate
   table silently escapes tuning — and every registry key must be a
   knob some env-read actually reads (no dead/typo'd entries).

AST-based (strings in comments can't trip it); imports nothing from the
package, so it runs anywhere.  Run directly or via
``tests/test_lint_fused_knobs.py``.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

#: call names whose string-literal argument is an env-knob read
_READ_FUNCS = frozenset({"get", "getenv", "pop", "fused_knob"})

#: covered knobs: the fused-op family, the kernel-scheduler knob, the
#: quant-calibration family, the fleet slot-scheduler pair
#: (STARK_FLEET_SLOTS pins the compiled batch shape, STARK_FLEET_WARMSTART
#: turns on donor-seeded admission warmup — each changes which executable
#: / how much warmup every admitted problem runs), and the
#: device-parallel fleet knob (STARK_FLEET_MESH shards the problem axis
#: over a mesh — a different compiled dispatch per shard), and the
#: comms-observatory switch (STARK_COMM_TELEMETRY=0 silences collective
#: accounting for byte-identical traces), and the elastic-fault-domain
#: pair (STARK_SHARD_DEADLINE arms the mesh fleet's shard deadman —
#: detection + degraded re-shard change the dispatch path;
#: STARK_FEED_MAXDEPTH bounds FleetFeed admission, changing what
#: `submit` does under load), and the posterior-serving read-plane
#: family (STARK_SERVE_* — serving.py's LRU capacity / telemetry switch
#: / sketch + predict caps, plus statusd's STARK_SERVE_ROOT auto-attach:
#: each changes what a read request serves or emits), and the tenant
#: lineage pair (STARK_LINEAGE=0 silences job_id stamping + the
#: feed_submit/slo_burn families for byte-identical traces;
#: STARK_TRACE_MAX_MB arms trace-file rotation, changing what lands in
#: which file) — extend the alternation when a new execution-path knob
#: family lands
_KNOB_RE = re.compile(
    r"^STARK_(?:FUSED_[A-Z0-9_]+|RAGGED_NUTS|QUANT_[A-Z0-9_]+"
    r"|FLEET_SLOTS|FLEET_WARMSTART|FLEET_MESH|COMM_TELEMETRY"
    r"|SHARD_DEADLINE|FEED_MAXDEPTH|SERVE_[A-Z0-9_]+|LINEAGE"
    r"|TRACE_MAX_MB|PROFILE(?:_[A-Z0-9_]+)?)$"
)

#: knobs the autotuner is responsible for: per-run execution-path
#: selectors a profile may set.  Every collected knob matching this
#: must appear in profile.CANDIDATE_SPACE (the autotuner's candidate
#: table) — a tunable knob outside the registry silently escapes
#: tuning.  Deliberately EXCLUDES the observability/serving switches
#: (telemetry, serving caps, fault deadlines, and the lineage pair
#: STARK_LINEAGE / STARK_TRACE_MAX_MB: they don't change which
#: executable a run picks) and the STARK_PROFILE* family itself (the
#: meta-knobs that resolve the profile can't live inside one).
_TUNABLE_RE = re.compile(
    r"^STARK_(?:FUSED_[A-Z0-9_]+|RAGGED_NUTS|QUANT_PCT"
    r"|FLEET_SLOTS|FLEET_WARMSTART|FLEET_MESH)$"
)


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def find_knob_reads(source: str, filename: str) -> List[Tuple[int, str]]:
    """(lineno, knob) for every STARK_FUSED_* literal in an env-read call."""
    tree = ast.parse(source, filename=filename)
    hits = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _call_name(node) in _READ_FUNCS):
            continue
        for arg in node.args:
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and _KNOB_RE.match(arg.value)
            ):
                hits.append((node.lineno, arg.value))
    return hits


def collect_knobs(pkg_dir: str) -> Dict[str, List[str]]:
    """knob -> ["path:line", ...] across the package."""
    knobs: Dict[str, List[str]] = {}
    for root, _dirs, files in os.walk(pkg_dir):
        if "__pycache__" in root:
            continue
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path) as f:
                source = f.read()
            for lineno, knob in find_knob_reads(source, path):
                knobs.setdefault(knob, []).append(f"{path}:{lineno}")
    return knobs


def _grep_tree(tree_dir: str, needles: Set[str]) -> Set[str]:
    """Which needles appear in any .py file under tree_dir."""
    found: Set[str] = set()
    for root, _dirs, files in os.walk(tree_dir):
        if "__pycache__" in root:
            continue
        for name in files:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(root, name)) as f:
                text = f.read()
            found.update(n for n in needles if n in text)
            if found == needles:
                return found
    return found


def candidate_space_keys(repo: str) -> Set[str]:
    """The ``CANDIDATE_SPACE`` dict-literal keys AST-parsed out of
    ``stark_tpu/profile.py`` (no import — the lint must run anywhere).
    Empty set when the module or the literal is absent."""
    path = os.path.join(repo, "stark_tpu", "profile.py")
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Name)
                and t.id == "CANDIDATE_SPACE"
                and isinstance(node.value, ast.Dict)
            ):
                return {
                    k.value for k in node.value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                }
    return set()


def lint_repo(repo: str) -> List[str]:
    """Violation strings for the whole repo; empty = clean."""
    knobs = collect_knobs(os.path.join(repo, "stark_tpu"))
    if not knobs:
        return ["no STARK_FUSED_*/STARK_RAGGED_NUTS/STARK_QUANT_* env "
                "reads found under stark_tpu/ — the collector itself is "
                "broken"]
    violations = []
    readme_path = os.path.join(repo, "README.md")
    readme = open(readme_path).read() if os.path.exists(readme_path) else ""
    tested = _grep_tree(os.path.join(repo, "tests"), set(knobs))
    for knob in sorted(knobs):
        where = knobs[knob][0]
        if knob not in readme:
            violations.append(
                f"{where}: {knob} is read but missing from the README "
                "coverage docs — document the knob (zoo table for fused "
                "knobs; Performance section for scheduler knobs)"
            )
        if knob not in tested:
            violations.append(
                f"{where}: {knob} is read but referenced by no test under "
                "tests/ — add a fallback / knob-off bit-identity test "
                "that names the knob"
            )
    # autotuner-registry completeness (both directions), when the
    # profile module exists in this tree (synthetic lint-test repos may
    # omit it): every tunable execution-path knob must appear in
    # profile.CANDIDATE_SPACE, and every registry key must be a knob
    # somebody actually reads
    space = candidate_space_keys(repo)
    if space:
        for knob in sorted(knobs):
            if _TUNABLE_RE.match(knob) and knob not in space:
                violations.append(
                    f"{knobs[knob][0]}: tunable knob {knob} is missing "
                    "from profile.CANDIDATE_SPACE — the autotuner "
                    "(tools/autotune.py) cannot set a knob outside its "
                    "candidate table, so it silently escapes tuning"
                )
        for key in sorted(space):
            if key not in knobs:
                violations.append(
                    f"stark_tpu/profile.py: CANDIDATE_SPACE key {key} is "
                    "read by no env-read call under stark_tpu/ — a dead "
                    "registry entry (typo'd knob name?)"
                )
    return violations


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = lint_repo(repo)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} STARK_FUSED_* knob violation(s) — see "
            "tools/lint_fused_knobs.py docstring",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Repo lint: every STARK_HEALTH* knob must be documented + tested.

The statistical-health observatory (``stark_tpu/health.py``) is driven by
a family of threshold knobs — the master ``STARK_HEALTH`` switch plus one
``STARK_HEALTH_<NAME>`` threshold per warning in the taxonomy.  Each knob
changes which warnings a run emits (and so what operators alert on): an
undocumented knob is invisible to the people tuning the warning floor,
and an untested one can silently lose its default or its opt-out path.
This lint closes both loops statically, mirroring
``tools/lint_fused_knobs.py``:

1. AST-collect every ``STARK_HEALTH*`` string literal passed to an
   env-read call (``os.environ.get`` / ``os.getenv`` / ``environ.pop``)
   under ``stark_tpu/``.
2. Fail if a collected knob is missing from the README (the warning
   taxonomy table in the Observability section is the operator
   contract), or
3. appears nowhere under ``tests/`` (every threshold needs a named test
   exercising it).

AST-based (strings in comments can't trip it); imports nothing from the
package, so it runs anywhere.  Run directly or via
``tests/test_lint_health_thresholds.py`` (tier-1).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

#: call names whose string-literal argument is an env-knob read
_READ_FUNCS = frozenset({"get", "getenv", "pop"})

#: the covered family: the master switch and every threshold knob
_KNOB_RE = re.compile(r"^STARK_HEALTH(?:_[A-Z0-9_]+)?$")


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def find_knob_reads(source: str, filename: str) -> List[Tuple[int, str]]:
    """(lineno, knob) for every STARK_HEALTH* literal in an env-read."""
    tree = ast.parse(source, filename=filename)
    hits = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _call_name(node) in _READ_FUNCS):
            continue
        for arg in node.args:
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and _KNOB_RE.match(arg.value)
            ):
                hits.append((node.lineno, arg.value))
    return hits


def collect_knobs(pkg_dir: str) -> Dict[str, List[str]]:
    """knob -> ["path:line", ...] across the package."""
    knobs: Dict[str, List[str]] = {}
    for root, _dirs, files in os.walk(pkg_dir):
        if "__pycache__" in root:
            continue
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path) as f:
                source = f.read()
            for lineno, knob in find_knob_reads(source, path):
                knobs.setdefault(knob, []).append(f"{path}:{lineno}")
    return knobs


def _grep_tree(tree_dir: str, needles: Set[str]) -> Set[str]:
    """Which needles appear in any .py file under tree_dir.

    Matched on word boundaries so ``STARK_HEALTH`` in a test does not
    silently satisfy every ``STARK_HEALTH_<NAME>`` threshold too."""
    found: Set[str] = set()
    pats = {n: re.compile(re.escape(n) + r"(?![A-Z0-9_])") for n in needles}
    for root, _dirs, files in os.walk(tree_dir):
        if "__pycache__" in root:
            continue
        for name in files:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(root, name)) as f:
                text = f.read()
            found.update(n for n, p in pats.items() if p.search(text))
            if found == needles:
                return found
    return found


def lint_repo(repo: str) -> List[str]:
    """Violation strings for the whole repo; empty = clean."""
    knobs = collect_knobs(os.path.join(repo, "stark_tpu"))
    if not knobs:
        return ["no STARK_HEALTH* env reads found under stark_tpu/ — the "
                "collector itself is broken"]
    violations = []
    readme_path = os.path.join(repo, "README.md")
    readme = open(readme_path).read() if os.path.exists(readme_path) else ""
    tested = _grep_tree(os.path.join(repo, "tests"), set(knobs))
    for knob in sorted(knobs):
        where = knobs[knob][0]
        # word-bounded like the tests grep: the bare STARK_HEALTH master
        # switch must not be satisfied by STARK_HEALTH_<NAME> mentions
        if not re.search(re.escape(knob) + r"(?![A-Z0-9_])", readme):
            violations.append(
                f"{where}: {knob} is read but missing from the README "
                "warning-taxonomy table (Observability section) — "
                "document the knob"
            )
        if knob not in tested:
            violations.append(
                f"{where}: {knob} is read but referenced by no test under "
                "tests/ — add a named test exercising the threshold "
                "(or the =0 opt-out for the master switch)"
            )
    return violations


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = lint_repo(repo)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} STARK_HEALTH* knob violation(s) — see "
            "tools/lint_health_thresholds.py docstring",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

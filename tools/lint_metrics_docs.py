#!/usr/bin/env python
"""Repo lint: every registered metric name must be documented in the README.

The metrics registry (``stark_tpu/metrics.py``) is the operator-facing
scrape contract — dashboards and alert rules are written against the
names it exposes at ``/metrics``.  A metric registered in code but
missing from the README metric table is invisible to operators exactly
like an undocumented env knob (the gap ``lint_fused_knobs.py`` closes
for knobs, and ``lint_trace_schema.py`` for event names).  This lint
closes it for metrics: AST-collect every name passed to a
``.counter("...")`` / ``.gauge("...")`` / ``.histogram("...")``
registration call in ``stark_tpu/metrics.py`` — including the
f-string form ``f"{p}_name"`` where ``p`` is the ``METRIC_PREFIX``
binding — and fail if any collected name does not appear in
``README.md`` (the metric table in the Observability section).

AST-based (names in comments or help strings can't trip it);
`stark_tpu.metrics` is imported only for ``METRIC_PREFIX`` (no jax),
so the lint runs anywhere.  Run directly
(``python tools/lint_metrics_docs.py``) or via the test suite
(``tests/test_lint_metrics_docs.py``).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stark_tpu.metrics import METRIC_PREFIX  # noqa: E402

#: registration attribute names whose first positional argument is the
#: metric name
_REGISTER_METHODS = frozenset({"counter", "gauge", "histogram"})


def _resolve_name(arg: ast.expr, prefix: str) -> Optional[str]:
    """The metric name a registration call's first argument denotes.

    Handles the two idioms the registry file uses: a plain string
    constant, and an f-string whose interpolations are simple names
    (the ``{p}`` / ``{METRIC_PREFIX}`` prefix binding) — any other
    interpolation makes the name non-static and returns None."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts: List[str] = []
        for v in arg.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif (
                isinstance(v, ast.FormattedValue)
                and isinstance(v.value, ast.Name)
                and v.value.id in ("p", "METRIC_PREFIX")
            ):
                # the prefix binding: f"{p}_..." / f"{METRIC_PREFIX}_..."
                parts.append(prefix)
            else:
                return None
        return "".join(parts)
    return None


def find_metric_names(source: str, filename: str,
                      prefix: str = METRIC_PREFIX) -> List[Tuple[int, str]]:
    """(lineno, metric_name) of every static registration call."""
    tree = ast.parse(source, filename=filename)
    hits = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _REGISTER_METHODS
            and node.args
        ):
            continue
        name = _resolve_name(node.args[0], prefix)
        if name is not None:
            hits.append((node.lineno, name))
    return hits


def lint_repo(repo: str) -> List[str]:
    """Violation strings for the whole repo; empty = clean."""
    metrics_path = os.path.join(repo, "stark_tpu", "metrics.py")
    with open(metrics_path) as f:
        names = find_metric_names(f.read(), metrics_path)
    if not names:
        return ["no metric registrations found in stark_tpu/metrics.py — "
                "the collector itself is broken"]
    readme_path = os.path.join(repo, "README.md")
    readme = open(readme_path).read() if os.path.exists(readme_path) else ""
    # the contract is the metric TABLE, not any prose mention: a name
    # that only survives in a curl example must still fail, so the
    # search is restricted to markdown table rows
    table_rows = "\n".join(
        line for line in readme.splitlines() if line.lstrip().startswith("|")
    )
    violations = []
    for lineno, name in sorted(set(names)):
        if name not in table_rows:
            violations.append(
                f"{metrics_path}:{lineno}: metric {name!r} is registered "
                "but missing from the README metric table — document it "
                "(a table row in the Observability section; prose or "
                "example mentions don't count)"
            )
    return violations


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = lint_repo(repo)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} undocumented metric(s) — see "
            "tools/lint_metrics_docs.py docstring",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

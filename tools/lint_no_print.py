#!/usr/bin/env python
"""Repo lint: no bare ``print()`` in stark_tpu/ library code.

Library diagnostics must go through ``logging`` (module logger) or the
telemetry trace — stdout/stderr prints from deep inside a sampler are
exactly the unstructured output the telemetry layer replaced.  CLI entry
points keep their machine interfaces: ``__main__.py`` (stdout JSON/tables)
and ``config.py`` (its ``__main__`` convenience block) are allowed.

AST-based, so strings/comments mentioning print don't trip it.  Run
directly (``python tools/lint_no_print.py``) or via the test suite
(``tests/test_lint_no_print.py``).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

#: files (relative to the package root) where print() is an interface
ALLOWED_FILES = frozenset({"__main__.py", "config.py"})


def find_prints(source: str, filename: str) -> List[Tuple[int, str]]:
    """(lineno, context) of every bare print() call in ``source``."""
    tree = ast.parse(source, filename=filename)
    hits = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            hits.append((node.lineno, ast.unparse(node)[:80]))
    return hits


def lint_package(pkg_dir: str) -> List[str]:
    """Violation strings ("path:line: call") for the whole package."""
    violations = []
    for root, _dirs, files in os.walk(pkg_dir):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(root, name), pkg_dir)
            if rel in ALLOWED_FILES:
                continue
            path = os.path.join(root, name)
            with open(path) as f:
                source = f.read()
            for lineno, ctx in find_prints(source, path):
                violations.append(f"{path}:{lineno}: {ctx}")
    return violations


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(repo, "stark_tpu")
    violations = lint_package(pkg)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} bare print() call(s) in library code — "
            "use the module logger (logging.getLogger) or the telemetry "
            "trace instead (see tools/lint_no_print.py docstring)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Repo lint: supervision boundaries must never eat Ctrl-C or SystemExit.

A retry loop that catches ``BaseException`` (or uses a bare ``except:``)
swallows KeyboardInterrupt and SystemExit — the operator's Ctrl-C becomes
"restart attempt N+1" and the run is unkillable, which is exactly the
failure mode the watchdog/supervision hardening exists to avoid.  The rule
for ``stark_tpu/``:

  * bare ``except:``, ``except BaseException``, and explicit
    ``except KeyboardInterrupt`` / ``except SystemExit`` handlers are
    allowed ONLY if the handler re-raises (a bare ``raise`` anywhere in
    its body) — cleanup-and-propagate is fine, catch-and-continue is not.
  * ``except Exception`` is the correct supervision-boundary catch and is
    never flagged.

AST-based, like its sibling ``tools/lint_no_print.py``; run directly or
via ``tests/test_lint_supervision.py`` (tier-1).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

#: exception names whose explicit capture requires a re-raise
_GUARDED = frozenset({"BaseException", "KeyboardInterrupt", "SystemExit"})


def _names(node) -> List[str]:
    """Exception class names an ExceptHandler's type expression mentions."""
    if node is None:
        return []
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Attribute):
        return [node.attr]
    if isinstance(node, ast.Tuple):
        out: List[str] = []
        for elt in node.elts:
            out.extend(_names(elt))
        return out
    return []


def _reraises(handler: ast.ExceptHandler) -> bool:
    """True iff the handler body contains a bare ``raise`` (re-raise)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise) and node.exc is None:
            return True
    return False


def find_violations(source: str, filename: str) -> List[Tuple[int, str]]:
    """(lineno, description) for every swallowing guarded handler."""
    tree = ast.parse(source, filename=filename)
    hits: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            what = "bare except:"
        else:
            guarded = sorted(set(_names(node.type)) & _GUARDED)
            if not guarded:
                continue
            what = f"except {', '.join(guarded)}"
        if not _reraises(node):
            hits.append((node.lineno, f"{what} without re-raise"))
    return hits


def lint_package(pkg_dir: str) -> List[str]:
    violations: List[str] = []
    for root, _dirs, files in os.walk(pkg_dir):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path) as f:
                source = f.read()
            for lineno, desc in find_violations(source, path):
                violations.append(f"{path}:{lineno}: {desc}")
    return violations


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations = lint_package(os.path.join(repo, "stark_tpu"))
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} handler(s) can swallow Ctrl-C/SystemExit — "
            "catch Exception at supervision boundaries, or re-raise "
            "(see tools/lint_supervision.py docstring)",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

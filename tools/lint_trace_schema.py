#!/usr/bin/env python
"""Repo lint: every emitted trace event name must be in the schema registry.

The telemetry schema grew three consumer layers — ``summarize_trace`` /
``trace_report``, the perf ledger, and the live metrics exporter — all
keyed on event NAMES.  A typo'd or undocumented ``emit("sampel_block")``
would silently vanish from every one of them (readers must tolerate
unknown types by the forward-compat rule, so nothing would ever raise).
This lint closes the loop: it statically collects every
``*.emit("<name>", ...)`` and ``*.phase("<name>", ...)`` call in
``stark_tpu/`` whose first argument is a string literal and fails if a
name is missing from `stark_tpu.telemetry.ALL_EVENT_TYPES` (the canonical
set plus the documented auxiliaries).  Non-literal first arguments (the
`_Phase` re-emit helper's variable) are skipped — the names they forward
were already collected at their literal call sites.

PR 15 extended coverage to the flight-recorder emission idiom: the
``record_anomaly(trigger, trace, "<event>", ...)`` sites (and their
``event=`` keyword form) emit trace records through the recorder rather
than a direct ``emit()`` call, so their event names — including the new
``health_warning`` family — are collected and checked too; before this,
a typo'd anomaly event name would have slipped past the lint.

PR 20 added a second axis: the tenant lineage observatory
(``stark_tpu/lineage.py``) partitions the registry into job_id-BEARING
event types (`lineage.JOB_EVENT_TYPES` — tenant-correlated, the record
annotator may stamp them) and EXEMPT ones (`lineage.EXEMPT_EVENT_TYPES`
— process-/fleet-global, never stamped).  The lint now also fails when
the two sets overlap, when a name in `ALL_EVENT_TYPES` sits in neither
(a new event family cannot land without deciding its lineage story),
or when either set classifies a name the registry doesn't know.

AST-based (strings/comments can't trip it); `stark_tpu.telemetry` and
`stark_tpu.lineage` import no jax at module load, so the lint runs
anywhere.  Run directly (``python tools/lint_trace_schema.py``) or via
the test suite (``tests/test_lint_trace_schema.py``).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stark_tpu.telemetry import ALL_EVENT_TYPES  # noqa: E402

#: emit-like attribute names whose first positional argument is an event
#: type from the schema registry
_EMIT_METHODS = frozenset({"emit", "phase"})


def find_event_names(source: str, filename: str) -> List[Tuple[int, str]]:
    """(lineno, event_name) of every literal emit()/phase() call, plus
    the event argument of ``record_anomaly(trigger, trace, "<event>")``
    flight-recorder sites (3rd positional or ``event=`` keyword)."""
    tree = ast.parse(source, filename=filename)
    hits = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
        ):
            continue
        if node.func.attr in _EMIT_METHODS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                hits.append((node.lineno, arg.value))
        elif node.func.attr == "record_anomaly":
            args = []
            if len(node.args) >= 3:
                args.append(node.args[2])
            args.extend(
                kw.value for kw in node.keywords if kw.arg == "event"
            )
            for arg in args:
                if (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                ):
                    hits.append((node.lineno, arg.value))
    return hits


def lint_package(pkg_dir: str) -> List[str]:
    """Violation strings ("path:line: name") for the whole package."""
    violations = []
    for root, _dirs, files in os.walk(pkg_dir):
        if "__pycache__" in root:
            continue
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path) as f:
                source = f.read()
            for lineno, event in find_event_names(source, path):
                if event not in ALL_EVENT_TYPES:
                    violations.append(f"{path}:{lineno}: {event!r}")
    return violations


def lint_lineage_partition() -> List[str]:
    """Violation strings for the lineage classification: every name in
    `ALL_EVENT_TYPES` must be in exactly one of
    `lineage.JOB_EVENT_TYPES` / `lineage.EXEMPT_EVENT_TYPES`, and
    neither set may classify a name the registry doesn't know."""
    from stark_tpu.lineage import EXEMPT_EVENT_TYPES, JOB_EVENT_TYPES

    violations = []
    for name in sorted(JOB_EVENT_TYPES & EXEMPT_EVENT_TYPES):
        violations.append(
            f"lineage: {name!r} is both job_id-bearing AND exempt — "
            "pick one"
        )
    for name in sorted(
        ALL_EVENT_TYPES - JOB_EVENT_TYPES - EXEMPT_EVENT_TYPES
    ):
        violations.append(
            f"lineage: {name!r} is unclassified — add it to "
            "lineage.JOB_EVENT_TYPES (tenant-correlated, annotator may "
            "stamp job_id) or lineage.EXEMPT_EVENT_TYPES "
            "(process-/fleet-global, never stamped)"
        )
    for name in sorted(
        (JOB_EVENT_TYPES | EXEMPT_EVENT_TYPES) - ALL_EVENT_TYPES
    ):
        violations.append(
            f"lineage: {name!r} is classified but missing from "
            "telemetry.ALL_EVENT_TYPES — stale classification?"
        )
    return violations


def main(argv=None) -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(repo, "stark_tpu")
    violations = lint_package(pkg)
    if violations:
        known = ", ".join(sorted(ALL_EVENT_TYPES))
        violations.append(
            f"{len(violations)} emit/phase call(s) with event names missing "
            f"from telemetry's schema registry (known: {known}) — add the "
            "event to EVENT_TYPES/AUX_EVENT_TYPES (and document it) or fix "
            "the name (see tools/lint_trace_schema.py docstring)"
        )
    violations.extend(lint_lineage_partition())
    for v in violations:
        print(v, file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

#!/bin/sh
# On-chip measurement set (r3-refreshed). Run when the axon tunnel is
# alive (probe: timeout 60 python -c "import jax; print(jax.devices())";
# relay listeners: ss -tln | grep 808).
#
# Rules (see DESIGN.md §4d and the tpu notes in memory):
#  - ONE TPU process at a time; never SIGTERM a TPU process mid-dispatch
#    (a killed client can wedge the relay for the whole session) — no
#    `timeout` wrappers here on purpose.
#  - A DEVICE FAULT can also wedge the relay (observed r3: a depth-7
#    monolithic NUTS program faulted and took the tunnel down for the
#    rest of the session). Keep device programs dispatch-bounded; do not
#    run experimental configs before the judged measurements are in.
#  - Measure per-eval costs with K >= 100 iterations amortized INSIDE one
#    program: the per-dispatch sync round-trip is ~108 ms, so K=10
#    sync-each timings are floor-dominated garbage.
#  - Each step is restartable; bench.py supervises/resumes itself.
set -ex

# 1. kernel roofline (memoization-gated methodology; rows above spec peak
#    are retried and otherwise tagged invalid) -> tools/roofline_results.json
#    r5: now includes the GROUPED kernel (the flagship's own kernel) with
#    attribution cases — grouped_full vs grouped_gather_hoist (alpha-window
#    gather cost) vs grouped_prec_high/default (MXU-pass cost of f32
#    emulation: HIGHEST=6 bf16 passes, HIGH=3, DEFAULT=1).  The pass-count
#    arithmetic (BASELINE.md r5) predicts the grouped kernel is MXU-bound
#    at HIGHEST; if grouped_prec_high cuts the eval materially, run
#    tools/precision_parity.py (below) and adopt the cheapest precision
#    whose posterior parity holds.
python tools/roofline.py

# 1b. precision parity: same grouped config at highest vs high, same
#     seed; adopt=high when max posterior-mean delta < 0.1 sd and both
#     converge -> then re-run step 3 with STARK_FUSED_PRECISION=high
python tools/precision_parity.py high
#     then the combined candidate (precision=high + bf16 X stream):
PARITY_X_DTYPE=bf16 python tools/precision_parity.py high

# 2. five judged configs -> appends the measured table to BASELINE.md
#    (r4: table now carries the BNN predictive_accuracy/pred-ESS and the
#    consensus combine_rel_err in a notes column)
python -m stark_tpu bench-all --update-baseline BASELINE.md

# 3. flagship (supervised ChEES, 1M rows, grouped kernel, C=64)
#    -> best-so-far JSON lines + phase breakdown; r3 measured 31.34
#    ESS/s/chip converged (see BASELINE.md flagship table).
#    r4: adaptation reuse is ON by default — if a committed
#    .bench_adapt_*.npz matches, warmup collapses to a 20% touch-up
#    (BENCH_ADAPT_REUSE=0 re-measures the cold-start path).  The first
#    on-chip run after a cold repo exports the artifact; run bench.py
#    TWICE when measuring the warm-start speedup.
python bench.py

# 4. config 2 at its pinned N=1M (consensus + combine-accuracy check)
python tools/consensus_1m.py --out BASELINE.md

# 5. EXPERIMENTS LAST (each could fault; judged numbers are already in):
#    a. C=128 grouped flagship: tile 8192 trips the VMEM guard at C=128,
#       so cap the tile — r3 measured C=64 at 19.2 ESS/s vs C=32 at 14.8
#       (sublinear); C=128 at tile 4096 is the untested next step:
#         STARK_GROUPED_LANE_TILE=4096 BENCH_CHEES_CHAINS=128 python bench.py
#    b. guard fault-boundary probe (VERDICT r4 #7): ONE expendable config
#       just over STARK_MAX_ROWGRADS_PER_PROGRAM (~2.5e11 row-grads), run
#       dead last — it may wedge the relay; turns the 2-point calibration
#       into a measured threshold either way.

#!/bin/sh
# Round-2 on-chip measurement set. Run when the axon tunnel is alive
# (probe: timeout 60 python -c "import jax; print(jax.devices())").
#
# Rules (see tpu notes in DESIGN.md / memory):
#  - ONE TPU process at a time; never SIGTERM a TPU process mid-dispatch
#    (a killed client can wedge the relay for the whole session) — no
#    `timeout` wrappers here on purpose.
#  - Each step is restartable; bench.py supervises/resumes itself.
set -ex

# 1. kernel roofline with the fixed timing methodology (distinct inputs,
#    warm input excluded, per-dispatch synced) -> tools/roofline_results.json
python tools/roofline.py

# 2. five judged configs -> appends the measured table to BASELINE.md
python -m stark_tpu bench-all --update-baseline BASELINE.md

# 3. flagship (supervised ChEES, 1M rows) -> one JSON line + phase breakdown
python bench.py

#!/usr/bin/env python
"""Cross-run performance regression ledger: ingest rows, gate on check.

    # append a row from a bench artifact (the final JSON line of bench.py)
    python tools/perf_ledger.py ingest --bench-json artifact.json \\
        --config "flagship:n=20000" --note "r6 capture"

    # append a row from a telemetry trace (any --trace'd run)
    python tools/perf_ledger.py ingest --trace /tmp/t.jsonl --config smoke

    # gate: newest row vs the trailing median of its config peers
    python tools/perf_ledger.py check              # exit 1 on regression
    python tools/perf_ledger.py check --strict --tolerance 0.15 --window 7
    python tools/perf_ledger.py show               # render the ledger

``ingest`` accepts ``--bench-json -`` to read the artifact from stdin
(``python bench.py | tail -1 | python tools/perf_ledger.py ingest ...``);
when a bench artifact AND a trace are both given the bench line wins per
metric.  The ledger lives at ``bench_artifacts/ledger.jsonl`` unless
``--ledger``/``STARK_PERF_LEDGER`` points elsewhere; ``bench.py``
auto-appends after every full run (STARK_PERF_LEDGER=0 opts out).

Row schema, tolerance semantics, and the trailing-median rule live in
`stark_tpu.ledger` (shared with the bench auto-append); the trace read
path reuses `telemetry.summarize_trace` — the same dict
``tools/trace_report.py --json`` emits.  Rows carry ``profile``
provenance (the active autotuned profile id, None otherwise); ``check``
treats differing profiles as distinct series — an autotuned run never
gates against the default-knob median.

"""

from __future__ import annotations

import argparse
import json
import os
import sys

# repo-root invocation without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stark_tpu import ledger  # noqa: E402


def _load_bench_json(arg: str):
    """The bench artifact dict from a file ('-' = stdin).  Accepts either
    a bare JSON object or bench.py's full stdout (takes the LAST
    parseable JSON line — the authoritative artifact line)."""
    text = sys.stdin.read() if arg == "-" else open(arg).read()
    text = text.strip()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            return rec
    raise SystemExit(f"no parseable JSON object in {arg!r}")


def _ledger_path(args) -> str:
    path = args.ledger or ledger.default_ledger_path()
    if path is None:
        raise SystemExit(
            f"ledger disabled ({ledger.LEDGER_ENV}=0) — pass --ledger PATH"
        )
    return path


def cmd_ingest(args) -> int:
    if not args.bench_json and not args.trace:
        raise SystemExit("ingest needs --bench-json and/or --trace")
    bench = _load_bench_json(args.bench_json) if args.bench_json else None
    summary = None
    if args.trace:
        from stark_tpu.telemetry import read_trace, summarize_trace

        summary = summarize_trace(read_trace(args.trace, strict=False))
    config = args.config
    if config is None and bench is not None:
        # the bench artifact's metric string identifies the workload
        config = str(bench.get("metric", "unknown"))
    row = ledger.make_row(
        source="perf_ledger ingest",
        config=config or "unknown",
        bench=bench,
        trace_summary=summary,
        note=args.note,
    )
    path = ledger.append_row(row, _ledger_path(args))
    print(json.dumps({"ingested": row, "ledger": path}))
    return 0


def cmd_check(args) -> int:
    path = _ledger_path(args)
    rows = ledger.read_rows(path)
    ok, report = ledger.check_rows(
        rows,
        window=args.window,
        tolerance=args.tolerance,
        min_history=args.min_history,
        strict=args.strict,
        config=args.config,
        all_configs=args.all_configs,
    )
    for line in report:
        print(line)
    if not ok:
        print(f"PERF REGRESSION ({path})", file=sys.stderr)
        return 1
    print("ok")
    return 0


def cmd_show(args) -> int:
    rows = ledger.read_rows(_ledger_path(args))
    if not rows:
        print("(empty ledger)")
        return 0
    cols = ("ts", "config", "profile", "git_sha", "ess_per_sec", "wall_s",
            "device_idle_frac", "overshoot_draws", "converged")
    for r in rows:
        print(json.dumps({k: r.get(k) for k in cols}))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--ledger", metavar="PATH", default=None,
        help="ledger file (default: bench_artifacts/ledger.jsonl, "
        f"override with {ledger.LEDGER_ENV})",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_in = sub.add_parser("ingest", help="append one row to the ledger")
    p_in.add_argument(
        "--bench-json", metavar="PATH",
        help="bench artifact JSON ('-' = stdin; bench.py stdout works, "
        "the last JSON line wins)",
    )
    p_in.add_argument(
        "--trace", metavar="PATH",
        help="telemetry trace to summarize into the row",
    )
    p_in.add_argument(
        "--config", default=None,
        help="comparability key (rows gate only against the same config)",
    )
    p_in.add_argument("--note", default=None)
    p_in.set_defaults(fn=cmd_ingest)

    p_ck = sub.add_parser(
        "check", help="gate the newest row vs the trailing median"
    )
    p_ck.add_argument("--window", type=int, default=5,
                      help="trailing rows in the median (default 5)")
    p_ck.add_argument("--tolerance", type=float, default=0.25,
                      help="allowed fractional slack (default 0.25)")
    p_ck.add_argument("--min-history", type=int, default=2,
                      help="prior rows required before gating (default 2)")
    p_ck.add_argument("--strict", action="store_true",
                      help="gate the efficiency metrics too, not just "
                      "ess_per_sec")
    gate = p_ck.add_mutually_exclusive_group()
    gate.add_argument(
        "--config", default=None,
        help="gate the newest row of THIS config (use when other "
        "configs may have appended after the run under test)",
    )
    gate.add_argument(
        "--all-configs", action="store_true",
        help="gate the newest row of every config in the ledger",
    )
    p_ck.set_defaults(fn=cmd_check)

    p_sh = sub.add_parser("show", help="print the ledger, one row per line")
    p_sh.set_defaults(fn=cmd_show)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

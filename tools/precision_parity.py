#!/usr/bin/env python
"""Zoo-wide precision-parity gate for the fused value-and-grad layer.

Two modes:

SWEEP (default: ``python tools/precision_parity.py`` or ``... sweep``)
    Every fused op in the zoo x {f32, bf16, int8, fp8e4m3, fp8e5m2}
    X-stream dtype x {default, high} MXU dot precision, each compared
    against the autodiff reference — the PLAIN model evaluated at
    f32/HIGHEST on the same rounded design matrix the fused path
    streams (bf16 rounds X once at prepare time; the quantized dtypes
    pack X with per-column calibrated scales, ops/quantize.py, and the
    reference sees the dequantized matrix; the posterior is exactly
    that of the rounded/dequantized matrix, so the reference must see
    it too).  Per cell the potential value and full gradient are
    compared at several parameter points and gated against the
    documented tolerance band:

      tight  f32 x high            val 1e-4, grad 1e-3
      mid    bf16 x high           val 5e-3, grad 2e-2
      wide   anything x default    val 2e-2, grad 5e-2
      quant  int8/fp8 x anything   val 2e-2, grad 5e-2

    (the quant band is wide-by-construction: the rounding itself is
    IN the reference, so the band only absorbs the epilogue-fold
    reordering — ``(beta*s)@q`` vs ``beta@(s*q)`` — plus the MXU's
    bf16-pass emulation at ``default``).  Quantized cells additionally
    carry a calibration-quality artifact column ``quant_col_err`` (max
    per-column relative quantization error of the packed X — how much
    data the calibration threw away, distinct from the parity delta,
    which measures the kernel).  On the CPU container f32 dots are
    exact at every precision, so measured deltas sit orders of
    magnitude inside the bands — the sweep there validates the
    HARNESS and the rounding/packing paths.  Writes
    tools/precision_parity_zoo.json (``_zoo_smoke.json`` on CPU) and
    exits non-zero if any cell fails — the acceptance gate for every
    STARK_FUSED_* knob and for adopting a cheaper precision setting.

SAMPLING (legacy: ``python tools/precision_parity.py high|default``)
    The original end-to-end posterior check: the grouped flagship
    model sampled at ``highest`` vs a candidate precision (same seed,
    same data), reporting posterior-mean deltas in posterior-sd units.
    Adoption rule unchanged: max mean-delta < 0.1 sd and both runs
    converged.  ``PARITY_X_DTYPE=bf16`` (or int8/fp8e4m3/fp8e5m2)
    additionally streams the candidate's X at that storage dtype.

Env: PARITY_SWEEP_N / _G / _D (sweep scale), PARITY_N / _D / _G /
_CHAINS / _WARMUP / _SAMPLES (sampling scale).
"""

import contextlib
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N = int(os.environ.get("PARITY_N", 200_000))
D = int(os.environ.get("PARITY_D", 32))
G = int(os.environ.get("PARITY_G", 1000))
CHAINS = int(os.environ.get("PARITY_CHAINS", 32))
WARMUP = int(os.environ.get("PARITY_WARMUP", 300))
SAMPLES = int(os.environ.get("PARITY_SAMPLES", 300))

SWEEP_N = int(os.environ.get("PARITY_SWEEP_N", 20_000))
SWEEP_D = int(os.environ.get("PARITY_SWEEP_D", 16))
SWEEP_G = int(os.environ.get("PARITY_SWEEP_G", 200))

#: (value_rel, grad_rel) tolerance bands, keyed by sweep cell class
TOLERANCE_BANDS = {
    "tight": (1e-4, 1e-3),
    "mid": (5e-3, 2e-2),
    "wide": (2e-2, 5e-2),
    # quantized X: the rounding is in the reference (rounded-X
    # convention), so this band only absorbs the epilogue-fold
    # reordering + dot-pass emulation — wide-sized to stay honest on
    # the TPU MXU, though CPU measures it orders of magnitude tighter
    "quant": (2e-2, 5e-2),
}

#: quantized X-stream dtypes (ops/quantize.py packed storage)
QUANT_X_DTYPES = ("int8", "fp8e4m3", "fp8e5m2")

#: the full sweep dtype axis — mirrors precision.X_DTYPE_NAMES
X_DTYPES = ("f32", "bf16") + QUANT_X_DTYPES


def band_for(x_dtype: str, precision: str) -> str:
    if x_dtype in QUANT_X_DTYPES:
        return "quant"
    if precision == "default":
        return "wide"
    return "mid" if x_dtype == "bf16" else "tight"


def zoo_cases():
    """(name, plain model, fused model, raw data, family knob or None)
    for every fused op — the zoo coverage table in code form (the README
    table and tools/lint_fused_knobs.py mirror it)."""
    import jax

    from stark_tpu.models import (
        FusedHierLogistic,
        FusedHierLogisticGrouped,
        FusedIRT2PL,
        FusedLMM,
        FusedLinearMixedModel,
        FusedLinearRegression,
        FusedLogistic,
        FusedOrderedLogistic,
        FusedPoissonRegression,
        FusedStudentTRegression,
        HierLogistic,
        IRT2PL,
        LinearMixedModel,
        LinearRegression,
        Logistic,
        OrderedLogistic,
        PoissonRegression,
        StudentTRegression,
        synth_irt_data,
        synth_linreg_data,
        synth_lmm_data,
        synth_logistic_data,
        synth_ordinal_data,
        synth_poisson_data,
        synth_studentt_data,
    )

    n, d, g = SWEEP_N, SWEEP_D, SWEEP_G
    key = jax.random.PRNGKey(0)
    dlog, _ = synth_logistic_data(key, n, d)
    dhier, _ = synth_logistic_data(key, n, d, num_groups=g)
    dlin, _ = synth_linreg_data(key, n, d)
    dpois, _ = synth_poisson_data(key, n, d)
    dlmm, _ = synth_lmm_data(key, n, d, g)
    p, i = max(n // 100, 20), 60
    dirt, _ = synth_irt_data(key, p, i)
    dord, _ = synth_ordinal_data(key, n, d)
    drob, _ = synth_studentt_data(key, n, d)
    return [
        ("logistic", Logistic(d), FusedLogistic(d), dlog, None),
        ("hier_logistic", HierLogistic(d, g), FusedHierLogistic(d, g),
         dhier, None),
        ("hier_logistic_grouped", HierLogistic(d, g),
         FusedHierLogisticGrouped(d, g), dhier, None),
        ("gaussian", LinearRegression(d), FusedLinearRegression(d),
         dlin, None),
        ("glm_poisson", PoissonRegression(d), FusedPoissonRegression(d),
         dpois, "STARK_FUSED_GLM"),
        ("lmm_offset", LinearMixedModel(d, g), FusedLinearMixedModel(d, g),
         dlmm, None),
        ("lmm", LinearMixedModel(d, g), FusedLMM(d, g), dlmm,
         "STARK_FUSED_LMM"),
        ("irt", IRT2PL(p, i), FusedIRT2PL(p, i), dirt, "STARK_FUSED_IRT"),
        ("ordinal", OrderedLogistic(d, 5), FusedOrderedLogistic(d, 5),
         dord, "STARK_FUSED_ORDINAL"),
        ("robust", StudentTRegression(d), FusedStudentTRegression(d),
         drob, "STARK_FUSED_ROBUST"),
    ]


@contextlib.contextmanager
def _env(**kv):
    prior = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: v for k, v in kv.items() if v is not None})
    try:
        yield
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _eval_points(fm, data, npoints=3, scale=0.4):
    import jax

    f = jax.jit(lambda z: fm.potential_and_grad(z, data))
    out = []
    for s in range(npoints):
        z = scale * s * jax.random.normal(jax.random.PRNGKey(s), (fm.ndim,))
        v, g = f(z)
        out.append((float(v), g))
    return out


def reference_points(plain, data, x_dtype):
    """The autodiff reference evals for one (op, x_dtype).

    The reference sees the SAME rounded design matrix the fused path
    streams: bf16 rounding — and int8/fp8 quantize-dequantize through
    the very calibration path `prepare_data` packs with — is a data
    change (by contract), not an arithmetic difference the gate should
    flag.  Independent of the `precision` axis, so `run_sweep` computes
    it once per (op, x_dtype) and shares it across that op's precision
    cells.
    """
    import jax
    import jax.numpy as jnp

    from stark_tpu.model import flatten_model, prepare_model_data

    ref_data = dict(data)
    if "x" in ref_data:
        if x_dtype == "bf16":
            ref_data["x"] = (
                jnp.asarray(ref_data["x"]).astype(jnp.bfloat16)
                .astype(jnp.float32)
            )
        elif x_dtype in QUANT_X_DTYPES:
            from stark_tpu.ops.quantize import fake_quant

            ref_data["x"] = fake_quant(ref_data["x"], x_dtype)
    with _env(STARK_FUSED_PRECISION="highest", STARK_FUSED_X_DTYPE="f32"):
        with jax.default_matmul_precision("highest"):
            fm_p = flatten_model(plain)
            dp = prepare_model_data(plain, ref_data)
            return _eval_points(fm_p, dp)


def sweep_cell(name, plain, fused, data, knob, x_dtype, precision,
               ref=None):
    """One (op, x_dtype, precision) parity cell -> result row dict."""
    import numpy as np

    from stark_tpu.model import flatten_model, prepare_model_data

    if ref is None:
        ref = reference_points(plain, data, x_dtype)
    env = {
        "STARK_FUSED_PRECISION": precision,
        "STARK_FUSED_X_DTYPE": x_dtype,
    }
    if knob:
        env[knob] = "1"
    with _env(**env):
        fm_f = flatten_model(fused)
        df = prepare_model_data(fused, data)
        cand = _eval_points(fm_f, df)
    val_rel = grad_rel = 0.0
    for (v0, g0), (v1, g1) in zip(ref, cand):
        val_rel = max(val_rel, abs(v0 - v1) / (1.0 + abs(v0)))
        g0, g1 = np.asarray(g0, np.float64), np.asarray(g1, np.float64)
        grad_rel = max(
            grad_rel,
            float(np.max(np.abs(g0 - g1)) / (1e-6 + np.max(np.abs(g0)))),
        )
    band = band_for(x_dtype, precision)
    tol_v, tol_g = TOLERANCE_BANDS[band]
    quant_col_err = None
    if x_dtype in QUANT_X_DTYPES and "x" in data:
        # calibration-quality artifact: how much of X the packing threw
        # away (max per-column relative quant error) — the DATA-side
        # number the parity delta (kernel-side, vs the same dequantized
        # X) deliberately excludes
        from stark_tpu.ops.quantize import quant_column_error

        quant_col_err = quant_column_error(data["x"], x_dtype)
    return {
        "op": name,
        "knob": knob,
        "x_dtype": x_dtype,
        "precision": precision,
        "band": band,
        "val_rel": val_rel,
        "grad_rel": grad_rel,
        "tol_val": tol_v,
        "tol_grad": tol_g,
        "quant_col_err": quant_col_err,
        "ok": bool(val_rel <= tol_v and grad_rel <= tol_g),
    }


def run_sweep(x_dtypes=X_DTYPES, precisions=("default", "high"),
              cases=None):
    """The full fused-op x dtype x precision grid -> (rows, all_ok)."""
    rows = []
    for name, plain, fused, data, knob in (cases or zoo_cases()):
        for x_dtype in x_dtypes:
            ref = reference_points(plain, data, x_dtype)
            for precision in precisions:
                row = sweep_cell(
                    name, plain, fused, data, knob, x_dtype, precision,
                    ref=ref,
                )
                rows.append(row)
                qerr = (
                    f" qerr={row['quant_col_err']:.2e}"
                    if row.get("quant_col_err") is not None
                    else ""
                )
                print(
                    f"[parity] {name:22s} x={x_dtype:7s} prec={precision:7s}"
                    f" band={row['band']:5s} val={row['val_rel']:.2e}"
                    f" grad={row['grad_rel']:.2e}{qerr}"
                    f" {'ok' if row['ok'] else 'FAIL'}",
                    file=sys.stderr,
                )
    return rows, all(r["ok"] for r in rows)


def sweep_main():
    import jax

    rows, ok = run_sweep()
    out = {
        "platform": jax.devices()[0].platform,
        "sweep_n": SWEEP_N, "sweep_d": SWEEP_D, "sweep_g": SWEEP_G,
        "cells": rows,
        "ok": ok,
    }
    # CPU smokes validate the harness, not the chip (f32 dots are exact
    # on CPU): keep them off the on-chip artifact path, as before
    name = (
        "precision_parity_zoo.json"
        if out["platform"] != "cpu"
        else "precision_parity_zoo_smoke.json"
    )
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    print(
        f"[parity] zoo sweep {'PASSED' if ok else 'FAILED'}: "
        f"{sum(r['ok'] for r in rows)}/{len(rows)} cells inside their "
        "tolerance bands",
        file=sys.stderr,
    )
    return 0 if ok else 1


# --- legacy end-to-end sampling mode ----------------------------------


def run_at(precision, model, data, x_dtype=None):
    import numpy as np

    import stark_tpu

    os.environ["STARK_FUSED_PRECISION"] = precision
    # force BOTH knobs unconditionally: an externally-exported
    # STARK_FUSED_X_DTYPE must not leak into the f32/highest baseline
    # (that would invert the comparison and mislabel the artifact)
    os.environ["STARK_FUSED_X_DTYPE"] = x_dtype or "f32"
    try:
        post = stark_tpu.sample(
            model, data, chains=CHAINS, kernel="chees",
            num_warmup=WARMUP, num_samples=SAMPLES,
            init_step_size=0.1, map_init_steps=200, seed=0,
        )
    finally:
        os.environ.pop("STARK_FUSED_PRECISION", None)
        os.environ.pop("STARK_FUSED_X_DTYPE", None)
    flat = np.asarray(post.draws_flat, np.float64)
    return {
        "mean": flat.mean(axis=(0, 1)),
        "sd": flat.std(axis=(0, 1)),
        "max_rhat": float(post.max_rhat()),
        "min_ess": float(post.min_ess()),
    }


def sampling_main(candidate):
    import jax
    import numpy as np

    from stark_tpu.models import FusedHierLogisticGrouped, synth_logistic_data

    print(
        f"[parity] grouped model N={N} D={D} G={G} C={CHAINS}; "
        f"highest vs {candidate}",
        file=sys.stderr,
    )
    model = FusedHierLogisticGrouped(num_features=D, num_groups=G)
    data, _ = synth_logistic_data(jax.random.PRNGKey(0), N, D, num_groups=G)

    # PARITY_X_DTYPE=bf16 additionally streams the candidate's X in bf16
    # (the stream-side lever; the baseline always runs f32/highest).
    # NOTE: prepare_data runs inside sample(), so the dtype takes effect
    # per-run — the two runs legitimately see different X roundings.
    x_dtype = os.environ.pop("PARITY_X_DTYPE", None)
    base = run_at("highest", model, data)
    cand = run_at(candidate, model, data, x_dtype=x_dtype)

    sd = np.maximum(base["sd"], 1e-12)
    delta = np.abs(cand["mean"] - base["mean"]) / sd
    sd_ratio = cand["sd"] / sd
    out = {
        "platform": jax.devices()[0].platform,
        "n": N, "d": D, "g": G, "chains": CHAINS,
        "candidate": candidate,
        "candidate_x_dtype": x_dtype or "f32",
        "max_mean_delta_sd": float(delta.max()),
        "mean_mean_delta_sd": float(delta.mean()),
        "sd_ratio_minmax": [float(sd_ratio.min()), float(sd_ratio.max())],
        "highest": {k: base[k] for k in ("max_rhat", "min_ess")},
        candidate: {k: cand[k] for k in ("max_rhat", "min_ess")},
        "adopt": bool(
            delta.max() < 0.1
            and base["max_rhat"] < 1.01
            and cand["max_rhat"] < 1.01
        ),
    }
    # CPU smokes validate the harness, not the chip (f32 dots are exact
    # on CPU, so delta is trivially 0): keep them off the on-chip
    # artifact path, mirroring tools/roofline.py
    tag = "_bf16x" if x_dtype else ""
    name = (
        f"precision_parity{tag}.json"
        if out["platform"] != "cpu"
        else "precision_parity_smoke.json"
    )
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    print(
        f"[parity] adopt={out['adopt']} (rule: max mean-delta "
        f"{out['max_mean_delta_sd']:.4f} < 0.1 sd and both converged)",
        file=sys.stderr,
    )
    return 0


def main():
    arg = sys.argv[1] if len(sys.argv) > 1 else "sweep"
    if len(sys.argv) > 2:
        # fail fast: silently ignoring extra args (e.g. a hoped-for
        # --n flag) would run the full-scale sweep and overwrite the
        # artifact under a config the caller never asked for
        print(f"usage: {sys.argv[0]} [sweep|highest|high|default] "
              f"(scale via PARITY_SWEEP_N/D/G env)", file=sys.stderr)
        return 2
    if arg in ("highest", "high", "default"):
        return sampling_main(arg)
    if arg != "sweep":
        print(f"usage: {sys.argv[0]} [sweep|highest|high|default]",
              file=sys.stderr)
        return 2
    return sweep_main()


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Posterior parity check for the fused-kernel dot-precision lever.

BASELINE.md r5's pass-count analysis predicts the grouped hierarchical
kernel is MXU-pass-bound at f32 HIGHEST (6 bf16 passes per dot), making
``STARK_FUSED_PRECISION=high|default`` worth ~1.6x/2.6x flagship
throughput — IF the posterior is unchanged.  This script is that check:
it runs the same grouped-model ChEES config at ``highest`` and at a
candidate precision (same seed, same data), then reports

  * per-coordinate posterior-mean delta in posterior-sd units (max/mean)
  * posterior-sd ratio (candidate / highest)
  * both runs' convergence diagnostics

Adoption rule (printed with the result): adopt the candidate when the
max mean-delta is under 0.1 sd — an order of magnitude inside MC error
at judged ESS — and both runs converge.  Runs on-chip after
``tools/onchip.sh`` step 1; ``PARITY_N`` etc. shrink it for CPU smokes.

Usage:  STARK candidate:  python tools/precision_parity.py high
        (writes tools/precision_parity.json and prints a summary)
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N = int(os.environ.get("PARITY_N", 200_000))
D = int(os.environ.get("PARITY_D", 32))
G = int(os.environ.get("PARITY_G", 1000))
CHAINS = int(os.environ.get("PARITY_CHAINS", 32))
WARMUP = int(os.environ.get("PARITY_WARMUP", 300))
SAMPLES = int(os.environ.get("PARITY_SAMPLES", 300))


def run_at(precision, model, data, x_dtype=None):
    import numpy as np

    import stark_tpu

    os.environ["STARK_FUSED_PRECISION"] = precision
    # force BOTH knobs unconditionally: an externally-exported
    # STARK_FUSED_X_DTYPE must not leak into the f32/highest baseline
    # (that would invert the comparison and mislabel the artifact)
    os.environ["STARK_FUSED_X_DTYPE"] = x_dtype or "f32"
    try:
        post = stark_tpu.sample(
            model, data, chains=CHAINS, kernel="chees",
            num_warmup=WARMUP, num_samples=SAMPLES,
            init_step_size=0.1, map_init_steps=200, seed=0,
        )
    finally:
        os.environ.pop("STARK_FUSED_PRECISION", None)
        os.environ.pop("STARK_FUSED_X_DTYPE", None)
    flat = np.asarray(post.draws_flat, np.float64)
    return {
        "mean": flat.mean(axis=(0, 1)),
        "sd": flat.std(axis=(0, 1)),
        "max_rhat": float(post.max_rhat()),
        "min_ess": float(post.min_ess()),
    }


def main():
    candidate = sys.argv[1] if len(sys.argv) > 1 else "high"
    import jax
    import numpy as np

    from stark_tpu.models import FusedHierLogisticGrouped, synth_logistic_data

    print(
        f"[parity] grouped model N={N} D={D} G={G} C={CHAINS}; "
        f"highest vs {candidate}",
        file=sys.stderr,
    )
    model = FusedHierLogisticGrouped(num_features=D, num_groups=G)
    data, _ = synth_logistic_data(jax.random.PRNGKey(0), N, D, num_groups=G)

    # PARITY_X_DTYPE=bf16 additionally streams the candidate's X in bf16
    # (the stream-side lever; the baseline always runs f32/highest).
    # NOTE: prepare_data runs inside sample(), so the dtype takes effect
    # per-run — the two runs legitimately see different X roundings.
    x_dtype = os.environ.pop("PARITY_X_DTYPE", None)
    base = run_at("highest", model, data)
    cand = run_at(candidate, model, data, x_dtype=x_dtype)

    sd = np.maximum(base["sd"], 1e-12)
    delta = np.abs(cand["mean"] - base["mean"]) / sd
    sd_ratio = cand["sd"] / sd
    out = {
        "platform": jax.devices()[0].platform,
        "n": N, "d": D, "g": G, "chains": CHAINS,
        "candidate": candidate,
        "candidate_x_dtype": x_dtype or "f32",
        "max_mean_delta_sd": float(delta.max()),
        "mean_mean_delta_sd": float(delta.mean()),
        "sd_ratio_minmax": [float(sd_ratio.min()), float(sd_ratio.max())],
        "highest": {k: base[k] for k in ("max_rhat", "min_ess")},
        candidate: {k: cand[k] for k in ("max_rhat", "min_ess")},
        "adopt": bool(
            delta.max() < 0.1
            and base["max_rhat"] < 1.01
            and cand["max_rhat"] < 1.01
        ),
    }
    # CPU smokes validate the harness, not the chip (f32 dots are exact
    # on CPU, so delta is trivially 0): keep them off the on-chip
    # artifact path, mirroring tools/roofline.py
    tag = "_bf16x" if x_dtype else ""
    name = (
        f"precision_parity{tag}.json"
        if out["platform"] != "cpu"
        else "precision_parity_smoke.json"
    )
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    print(
        f"[parity] adopt={out['adopt']} (rule: max mean-delta "
        f"{out['max_mean_delta_sd']:.4f} < 0.1 sd and both converged)",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Roofline measurement for the fused logistic kernel (VERDICT r1 #2).

Separates DEVICE-EXECUTE time from tunnel/dispatch overhead without trace
parsing: time the chain-batched fused gradient (a) dispatched individually
(block_until_ready per call — what a naive per-step driver pays) and
(b) amortized K iterations inside ONE compiled lax.fori_loop (what the
production scan-based samplers actually execute).  The difference is the
per-dispatch overhead; (b) gives kernel-only GB/s.

Also measures a plain-XLA reduction over the same X matrix inside one
program — the achievable HBM streaming rate for this shape on this chip —
so %-of-achievable is reported next to %-of-spec-sheet-peak.

Run on the real chip (the axon platform):  python tools/roofline.py
Writes tools/roofline_results.json and prints a summary.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N = int(os.environ.get("ROOF_N", 1_000_000))
D = int(os.environ.get("ROOF_D", 32))
K = int(os.environ.get("ROOF_K", 20))  # amortized iterations per program
REPS = int(os.environ.get("ROOF_REPS", 10))
V5E_PEAK_GBS = 819.0  # v5e HBM spec
# ROOF_INTERPRET=1: Pallas interpret mode at tiny shapes — a CPU smoke of
# the measurement harness itself (rates are meaningless there; the on-chip
# run uses compiled kernels)
INTERPRET = os.environ.get("ROOF_INTERPRET", "") == "1"
SANITY_ATTEMPTS = 3


def gate(entry, *, peak_gbs=V5E_PEAK_GBS):
    """Memoization sanity gate (VERDICT r2 #3).

    A measured HBM rate above the chip's spec peak is physically impossible
    — it means the axon tunnel served at least one timed rep from its
    (executable, args) cache instead of executing it.  Tag such entries
    ``invalid_memoized`` so they can never be mistaken for real data, and
    null the %-of-peak field.  Returns True when the entry is sane.
    """
    rates = [entry.get("per_dispatch_gbs", 0.0), entry.get("amortized_gbs", 0.0)]
    if any(r > peak_gbs for r in rates):
        entry["invalid_memoized"] = True
        if "pct_of_spec_peak" in entry:
            entry["pct_of_spec_peak"] = None
        return False
    return True


def timeit(fn, warm_arg, arglist, *, sync_each=False):
    """Average seconds per call over DISTINCT inputs.

    Identical (executable, args) re-executions are memoized by the axon
    tunnel runtime (measured: a repeated 128 MB reduction 'ran' in 0.02 ms
    — 7 TB/s, physically impossible), so every timed rep must pass a fresh
    argument value — and the warm-up input must NOT be in the timed list,
    or its rep returns from the cache.  sync_each=True blocks per call
    (dispatch+sync latency, what a naive per-step driver pays);
    sync_each=False blocks once at the end (pipelined throughput).
    """
    jax.block_until_ready(fn(warm_arg))  # compile + warm
    t0 = time.perf_counter()
    if sync_each:
        for a in arglist:
            jax.block_until_ready(fn(a))
    else:
        jax.block_until_ready([fn(a) for a in arglist])
    return (time.perf_counter() - t0) / len(arglist)


def main():
    from stark_tpu.ops.logistic_fused import _batched_call

    platform = jax.devices()[0].platform
    print(f"[roofline] platform={platform} N={N} D={D} K={K}", file=sys.stderr)
    key = jax.random.PRNGKey(0)
    xt = jax.random.normal(key, (D, N), jnp.float32)
    y = (jax.random.uniform(jax.random.PRNGKey(1), (N,)) < 0.5).astype(jnp.float32)
    results = {"platform": platform, "n": N, "d": D, "k": K, "cases": []}

    # --- pure-XLA HBM stream baseline: sum(xt*s) amortized in one program ---
    @jax.jit
    def stream_once(s):
        return jnp.sum(xt * s)

    @jax.jit
    def stream_loop(s):
        def body(i, acc):
            # acc feeds back so iterations cannot be collapsed
            return acc + jnp.sum(xt * (s + 1e-9 * acc))

        return jax.lax.fori_loop(0, K, body, jnp.float32(0))

    def measure_gated(tag, measure_attempt):
        """Run measure_attempt(attempt) -> entry until the sanity gate
        passes (fresh inputs each attempt so a cache-tainted retry cannot
        replay earlier (executable, args) pairs); keep the last entry —
        tagged invalid_memoized — if every attempt is impossible."""
        for attempt in range(SANITY_ATTEMPTS):
            entry = measure_attempt(attempt)
            if gate(entry):
                return entry
            print(
                f"[roofline] {tag} attempt {attempt}: rate above spec peak "
                f"(memoized) — regenerating inputs and retrying",
                file=sys.stderr,
            )
        return entry

    def invalid_or(entry, text):
        return "INVALID (memoized)" if entry.get("invalid_memoized") else text

    xt_bytes = xt.size * 4

    def stream_attempt(attempt):
        base = 1.0 + attempt * 0.37
        scales = [jnp.float32(base + i * 1e-6) for i in range(REPS)]
        warm_s = jnp.float32(base - 0.5)
        t1 = timeit(stream_once, warm_s, scales, sync_each=True)
        tk = timeit(stream_loop, warm_s, scales) / K
        return {
            "bytes": xt_bytes,
            "per_dispatch_s": t1,
            "amortized_s": tk,
            "per_dispatch_gbs": xt_bytes / t1 / 1e9,
            "amortized_gbs": xt_bytes / tk / 1e9,
        }

    stream = results["stream"] = measure_gated("stream", stream_attempt)
    print(
        f"[roofline] plain XLA sum over {xt_bytes/1e6:.0f} MB: "
        f"per-dispatch {stream['per_dispatch_s']*1e3:.2f} ms, "
        f"amortized {stream['amortized_s']*1e3:.2f} ms "
        + invalid_or(stream, f"({stream['amortized_gbs']:.0f} GB/s)"),
        file=sys.stderr,
    )

    for C in (8, 32, 64):
        beta = 0.01 * jax.random.normal(jax.random.PRNGKey(2), (C, D), jnp.float32)
        offsets = jnp.zeros((C, N), jnp.float32)

        @jax.jit
        def one(beta):
            v, g, r = _batched_call(
                beta, xt, y, offsets, lane_tile=None, interpret=INTERPRET
            )
            return v, g

        @jax.jit
        def loop(beta):
            def body(i, b):
                v, g, r = _batched_call(
                    b, xt, y, offsets, lane_tile=None, interpret=INTERPRET
                )
                # feed the gradient back so no iteration can be elided
                return b + 1e-12 * g

            return jax.lax.fori_loop(0, K, body, beta)

        # bytes: read xt + y + offsets, write resid (+ tiny partials)
        nbytes = xt_bytes + 4 * N + 4 * N * C + 4 * N * C

        def case_attempt(attempt, C=C, one=one, loop=loop, nbytes=nbytes):
            betas = [
                0.01
                * jax.random.normal(
                    jax.random.PRNGKey(10 + 1000 * attempt + i), (C, D), jnp.float32
                )
                for i in range(REPS + 1)
            ]
            t1 = timeit(one, betas[0], betas[1:], sync_each=True)
            tk = timeit(loop, betas[0], betas[1:]) / K
            return {
                "chains": C,
                "bytes": nbytes,
                "per_dispatch_s": t1,
                "amortized_s": tk,
                "per_dispatch_gbs": nbytes / t1 / 1e9,
                "amortized_gbs": nbytes / tk / 1e9,
                "dispatch_overhead_ms": (t1 - tk) * 1e3,
                "pct_of_spec_peak": 100.0 * nbytes / tk / 1e9 / V5E_PEAK_GBS,
            }

        case = measure_gated(f"C={C}", case_attempt)
        results["cases"].append(case)
        if case.get("invalid_memoized"):
            rate_str = "INVALID (memoized)"  # pct is None — don't format it
        else:
            rate_str = (
                f"({case['amortized_gbs']:.0f} GB/s = "
                f"{case['pct_of_spec_peak']:.0f}% of v5e spec peak)"
            )
        print(
            f"[roofline] C={C}: {nbytes/1e6:.0f} MB/eval; per-dispatch "
            f"{case['per_dispatch_s']*1e3:.2f} ms, amortized "
            f"{case['amortized_s']*1e3:.2f} ms " + rate_str
            + f"; dispatch overhead {case['dispatch_overhead_ms']:.2f} ms",
            file=sys.stderr,
        )

    # --- grouped hierarchical kernel (the kernel the FLAGSHIP runs on) ---
    # VERDICT r4 missing #5: the grouped kernel moves ~137 MB/eval in a
    # measured 2.1 ms (~65 GB/s effective) while the offset kernel above
    # streams at ~326 GB/s.  Pass-count arithmetic says the grouped
    # kernel is MXU-pass-bound, not HBM-bound: it runs FOUR f32 dots per
    # tile (logits: beta + alpha-window one-hot; gradients: X-weighted +
    # one-hot-weighted) and HIGHEST f32 precision is emulated in 6 bf16
    # MXU passes at C/128 row utilization — ~12.3 GFLOP/eval x 6 passes
    # / (32/128 rows) ~ 1.5 ms at the v5e's ~200 bf16 TFLOPs, vs 0.42 ms
    # for the 137 MB stream at the measured 326 GB/s.  Three cases
    # attribute the non-stream time on-chip:
    #   grouped_full         production ensemble gradient (gather+kernel+
    #                        scatter+sums)
    #   grouped_gather_hoist alpha fixed across iterations, so XLA hoists
    #                        the alpha-window gather out of the loop —
    #                        full minus this = gather cost
    #   grouped_prec_high    STARK_FUSED_PRECISION=high (3-pass dots) —
    #                        full minus this = MXU-pass cost (the lever)
    import stark_tpu.ops.hier_fused as hf

    G = int(os.environ.get("ROOF_G", 1000))
    gsorted = np.sort(np.arange(N) % G).astype(np.int32)
    layout = hf.grouped_layout(gsorted, D)
    if layout is None:
        print("[roofline] grouped layout infeasible at this shape; skipped",
              file=sys.stderr)
    grouped_cases = []
    if layout is not None:
        lane_tile, k_loc, first_gid, gl = layout
        gl_j = jnp.asarray(gl)
        fg_j = jnp.asarray(first_gid)
        C = int(os.environ.get("ROOF_GROUPED_C", 32))
        grid = -(-N // lane_tile)
        # xt + y + gl + alpha windows + (val, gbeta, galpha) partials
        gbytes = (
            xt.size * 4 + N * 4 + N * 4
            + grid * C * k_loc * 4
            + grid * C * (1 + D + k_loc) * 4
        )

        def make_case(tag, vary_alpha, precision, xt_case, case_bytes):
            def grouped_grad(beta, alpha):
                return hf._grouped_call(
                    beta, alpha, xt_case, y, gl_j, fg_j, k_loc=k_loc,
                    lane_tile=lane_tile, interpret=INTERPRET,
                )

            def attempt(attempt_i):
                prior = os.environ.get("STARK_FUSED_PRECISION")
                os.environ["STARK_FUSED_PRECISION"] = precision
                try:
                    @jax.jit
                    def one(beta, alpha):
                        return grouped_grad(beta, alpha)

                    @jax.jit
                    def loop(beta, alpha):
                        def body(i, ba):
                            b, a = ba
                            v, gb, ga = grouped_grad(b, a)
                            # feed gradients back so no iteration elides;
                            # alpha fixed in the hoist case so the window
                            # gather is loop-invariant
                            b = b + 1e-12 * gb
                            if vary_alpha:
                                a = a + 1e-12 * ga
                            return (b, a)

                        return jax.lax.fori_loop(0, K, body, (beta, alpha))

                    keys = [
                        jax.random.PRNGKey(77 + 1000 * attempt_i + i)
                        for i in range(2 * (REPS + 1))
                    ]
                    betas = [
                        0.01 * jax.random.normal(k, (C, D), jnp.float32)
                        for k in keys[: REPS + 1]
                    ]
                    alphas = [
                        0.01 * jax.random.normal(k, (C, G), jnp.float32)
                        for k in keys[REPS + 1 :]
                    ]
                    t1 = timeit(
                        lambda ba: one(*ba), (betas[0], alphas[0]),
                        list(zip(betas[1:], alphas[1:])), sync_each=True,
                    )
                    tk = timeit(
                        lambda ba: loop(*ba), (betas[0], alphas[0]),
                        list(zip(betas[1:], alphas[1:])),
                    ) / K
                finally:
                    # restore, don't pop: a session-level setting must
                    # survive this case (rows record their own precision)
                    if prior is None:
                        os.environ.pop("STARK_FUSED_PRECISION", None)
                    else:
                        os.environ["STARK_FUSED_PRECISION"] = prior
                return {
                    "case": tag,
                    "chains": C,
                    "lane_tile": lane_tile,
                    "k_loc": k_loc,
                    "precision": precision,
                    "x_dtype": str(xt_case.dtype),
                    "bytes": case_bytes,
                    "per_dispatch_s": t1,
                    "amortized_s": tk,
                    "per_dispatch_gbs": case_bytes / t1 / 1e9,
                    "amortized_gbs": case_bytes / tk / 1e9,
                    "pct_of_spec_peak": (
                        100.0 * case_bytes / tk / 1e9 / V5E_PEAK_GBS
                    ),
                }

            return attempt

        # bf16 X stream: halves the dominant X bytes (the stream-side
        # lever that compounds with the precision lever once the kernel
        # stops being MXU-pass-bound)
        xt_b16 = xt.astype(jnp.bfloat16)
        gbytes_b16 = gbytes - xt.size * 2
        for tag, vary_alpha, precision, xt_case, case_bytes in (
            ("grouped_full", True, "highest", xt, gbytes),
            ("grouped_gather_hoist", False, "highest", xt, gbytes),
            ("grouped_prec_high", True, "high", xt, gbytes),
            ("grouped_prec_default", True, "default", xt, gbytes),
            ("grouped_x_bf16_prec_high", True, "high", xt_b16, gbytes_b16),
        ):
            case = measure_gated(
                tag, make_case(tag, vary_alpha, precision, xt_case, case_bytes)
            )
            grouped_cases.append(case)
            rate = invalid_or(
                case,
                f"({case['amortized_gbs']:.0f} GB/s effective = "
                f"{case['pct_of_spec_peak']:.0f}% of v5e spec peak)",
            )
            print(
                f"[roofline] {tag}: {case_bytes/1e6:.0f} MB/eval; amortized "
                f"{case['amortized_s']*1e3:.2f} ms " + rate,
                file=sys.stderr,
            )
        full = grouped_cases[0]
        if not full.get("invalid_memoized") and not stream.get(
            "invalid_memoized"
        ):
            # non-stream time: measured amortized eval minus the time the
            # achievable stream rate needs for the same bytes.  Requires a
            # SANE stream baseline — a memoized stream rate would silently
            # overstate this, the very number the MXU-vs-DMA attribution
            # turns on
            full["non_stream_ms"] = (
                full["amortized_s"]
                - gbytes / (stream["amortized_gbs"] * 1e9)
            ) * 1e3
    results["grouped"] = grouped_cases

    # --- grouped LMM kernel (judged config 3's kernel) -------------------
    # Same MXU-pass argument (4+Q HIGHEST dots per tile); these rows let
    # the one on-chip session quantify the precision lever for config 3
    # alongside the flagship kernel.  Dense grouping (~10 rows/group)
    # shrinks the lane tile, so per-tile fixed costs matter more here.
    LN = int(os.environ.get("ROOF_LMM_N", 100_000))
    LD = int(os.environ.get("ROOF_LMM_D", 8))
    LG = int(os.environ.get("ROOF_LMM_G", 10_000))
    LQ = 2
    LC = int(os.environ.get("ROOF_LMM_C", 16))
    lmm_cases = []
    g_l = np.sort(np.arange(LN) % LG).astype(np.int32)
    lmm_layout = hf.grouped_layout(g_l, LD + LQ + 2)
    if lmm_layout is None:
        print("[roofline] grouped-LMM layout infeasible; skipped",
              file=sys.stderr)
    else:
        lt_l, kloc_l, fg_l, gl_l = lmm_layout
        grid_l = -(-LN // lt_l)
        xt_l = jax.random.normal(jax.random.PRNGKey(5), (LD, LN), jnp.float32)
        zt_l = jax.random.normal(jax.random.PRNGKey(6), (LQ, LN), jnp.float32)
        y_l = jax.random.normal(jax.random.PRNGKey(7), (LN,), jnp.float32)
        gl_lj, fg_lj = jnp.asarray(gl_l), jnp.asarray(fg_l)
        lbytes = (
            (LD + LQ + 2) * LN * 4                      # xt + zt + y + gl
            + grid_l * LC * LQ * kloc_l * 4             # u windows in
            + grid_l * LC * (2 + LD + LQ * kloc_l) * 4  # partials out
        )

        def make_lmm_case(tag, precision):
            def lmm_grad(beta, u, ic):
                return hf._grouped_lmm_call(
                    beta, u, ic, xt_l, zt_l, y_l, gl_lj, fg_lj,
                    k_loc=kloc_l, lane_tile=lt_l, interpret=INTERPRET,
                )

            def attempt(attempt_i):
                prior = os.environ.get("STARK_FUSED_PRECISION")
                os.environ["STARK_FUSED_PRECISION"] = precision
                try:
                    @jax.jit
                    def loop(beta, u, ic):
                        def body(i, bui):
                            b, uu, i0 = bui
                            ssr, sresid, gb, gu = lmm_grad(b, uu, i0)
                            return (
                                b + 1e-12 * gb,
                                uu + 1e-12 * gu,
                                i0 + 1e-12 * sresid,
                            )

                        return jax.lax.fori_loop(0, K, body, (beta, u, ic))

                    @jax.jit
                    def one(beta, u, ic):
                        return lmm_grad(beta, u, ic)

                    args = [
                        (
                            0.01 * jax.random.normal(
                                jax.random.PRNGKey(900 + 1000 * attempt_i + i),
                                (LC, LD), jnp.float32,
                            ),
                            0.01 * jax.random.normal(
                                jax.random.PRNGKey(950 + 1000 * attempt_i + i),
                                (LC, LG, LQ), jnp.float32,
                            ),
                            jnp.zeros((LC,), jnp.float32) + 0.01 * i,
                        )
                        for i in range(REPS + 1)
                    ]
                    t1 = timeit(
                        lambda a: one(*a), args[0], args[1:], sync_each=True
                    )
                    tk = timeit(lambda a: loop(*a), args[0], args[1:]) / K
                finally:
                    if prior is None:
                        os.environ.pop("STARK_FUSED_PRECISION", None)
                    else:
                        os.environ["STARK_FUSED_PRECISION"] = prior
                return {
                    "case": tag,
                    "chains": LC,
                    "lane_tile": lt_l,
                    "k_loc": kloc_l,
                    "precision": precision,
                    "bytes": lbytes,
                    "per_dispatch_s": t1,
                    "amortized_s": tk,
                    "per_dispatch_gbs": lbytes / t1 / 1e9,
                    "amortized_gbs": lbytes / tk / 1e9,
                    "pct_of_spec_peak": (
                        100.0 * lbytes / tk / 1e9 / V5E_PEAK_GBS
                    ),
                }

            return attempt

        for tag, precision in (
            ("lmm_grouped_full", "highest"),
            ("lmm_grouped_prec_high", "high"),
        ):
            case = measure_gated(tag, make_lmm_case(tag, precision))
            lmm_cases.append(case)
            rate = invalid_or(
                case,
                f"({case['amortized_gbs']:.0f} GB/s effective = "
                f"{case['pct_of_spec_peak']:.0f}% of v5e spec peak)",
            )
            print(
                f"[roofline] {tag}: {lbytes/1e6:.0f} MB/eval; amortized "
                f"{case['amortized_s']*1e3:.2f} ms " + rate,
                file=sys.stderr,
            )
    results["grouped_lmm"] = lmm_cases

    # interpret/CPU smoke runs must never overwrite the committed on-chip
    # artifact (tests pin its sanity) — they validate the harness, not
    # the chip
    name = (
        "roofline_results.json"
        if not INTERPRET and platform != "cpu"
        else "roofline_smoke.json"
    )
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps({"wrote": out_path}))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Render the attributed run timeline from a telemetry trace.

    python tools/timeline_report.py /tmp/t.jsonl            # last run
    python tools/timeline_report.py /tmp/t.jsonl --run 1    # specific run
    python tools/timeline_report.py /tmp/t.jsonl --all      # every run
    python tools/timeline_report.py /tmp/t.jsonl --json     # machine-readable
    python tools/timeline_report.py /tmp/t.jsonl --spans    # raw span list

Where ``tools/trace_report.py`` answers "what happened", this answers
"where did every wall-second go": the run decomposes into non-
overlapping, kind-tagged spans — compile / warmup / dispatch /
host_hidden / device_idle / checkpoint / comm / host — derived by
`stark_tpu.profiling` from the trace's phase events (or read directly
from ``span`` events when the writer recorded them via
STARK_PROFILE_SPANS).  The coverage line states how much of the run
wall the attribution accounts for; healthy post-PR-3 traces tile >=95%,
and the remainder is host-driver slack between phases.

Forward/backward compat: traces that predate a field (PR-1-era files
carry no overlap split; any pre-PR-11 trace carries no ``span``
events) render coarser attribution or ``n/a`` — never an error.
``--json`` emits the `profiling.timeline_summary` dict, the machine
contract ``bench.py`` stamps into perf-ledger rows (``compile_s`` /
``dispatch_count`` / ``span_coverage_frac``).  Stdlib-only read path
(no jax import), so it runs anywhere the trace file lands.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# repo-root invocation without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stark_tpu.profiling import (  # noqa: E402
    SPAN_KINDS,
    spans_from_events,
    timeline_summary,
)
from stark_tpu.telemetry import read_trace  # noqa: E402


def _fmt(v) -> str:
    if v is None:
        return "n/a"
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _table(rows, header) -> str:
    cols = [header] + [[_fmt(c) for c in r] for r in rows]
    widths = [max(len(r[i]) for r in cols) for i in range(len(header))]
    lines = []
    for j, r in enumerate(cols):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if j == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def render_run(events, run, show_spans=False) -> str:
    s = timeline_summary(events, run=run)
    out = []
    wall = s["wall_s"]
    cov = s["span_coverage_frac"]
    out.append(
        f"run {s['run']}: wall {_fmt(wall)}s, "
        f"attributed {_fmt(cov if cov is None else 100.0 * cov)}"
        + ("%" if cov is not None else "")
        + (" (spans synthesized from phase events)"
           if s["synthesized"] else " (literal span events)")
    )
    out.append(
        f"compile {_fmt(s['compile_s'])}s, "
        f"device dispatches {_fmt(s['dispatch_count'])}"
    )
    if s.get("x_dtype") is not None:
        # quantized/bf16 X streaming (ops/quantize.py); n/a-safe on
        # pre-quant traces (the key is simply absent there)
        out.append(
            f"x stream {s['x_dtype']}, "
            f"{_fmt(s.get('x_bytes_per_grad'))} bytes per gradient eval"
        )
    out.append("")
    by_kind = s["by_kind"]
    if not by_kind:
        out.append("(no attributable phase events in this run)")
        return "\n".join(out)
    order = {k: i for i, k in enumerate(SPAN_KINDS)}
    rows = [
        (
            kind,
            int(k["count"]),
            round(k["total_s"], 3),
            f"{100.0 * k['frac']:.1f}%" if k.get("frac") is not None else None,
        )
        for kind, k in sorted(
            by_kind.items(), key=lambda kv: order.get(kv[0], 99)
        )
    ]
    if wall is not None and cov is not None:
        un = max(wall - sum(k["total_s"] for k in by_kind.values()), 0.0)
        rows.append(("(unattributed)", None, round(un, 3),
                     f"{100.0 * un / wall:.1f}%" if wall else None))
    out.append(_table(rows, ("span kind", "spans", "total_s", "share")))
    if show_spans:
        tl = spans_from_events(events, run=run)
        out.append("")
        out.append(_table(
            [
                (
                    sp["kind"],
                    round(sp["start"], 3),
                    round(sp["end"], 3),
                    round(sp["dur"], 4),
                    sp.get("src"),
                    sp.get("block"),
                )
                for sp in tl["spans"]
            ],
            ("kind", "start_s", "end_s", "dur_s", "src", "block"),
        ))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="JSONL trace file")
    ap.add_argument("--run", type=int, default=None,
                    help="run ordinal to report (default: last)")
    ap.add_argument("--all", action="store_true", help="report every run")
    ap.add_argument("--json", action="store_true",
                    help="print the timeline_summary dict(s) as JSON")
    ap.add_argument("--spans", action="store_true",
                    help="also list every attributed span")
    args = ap.parse_args(argv)

    # tolerate a torn final line: the trace may still be live
    try:
        events = read_trace(args.trace, strict=False)
    except OSError as e:
        print(f"{args.trace}: {e}", file=sys.stderr)
        return 1
    if not events:
        print(f"{args.trace}: no parseable events", file=sys.stderr)
        return 1
    runs = sorted({e.get("run", 0) for e in events})
    picked = (
        runs if args.all
        else [args.run if args.run is not None else runs[-1]]
    )
    if args.json:
        out = [timeline_summary(events, run=r) for r in picked]
        print(json.dumps(out[0] if len(out) == 1 else out, indent=1))
        return 0
    chunks = [render_run(events, r, show_spans=args.spans) for r in picked]
    print(("\n\n" + "=" * 60 + "\n\n").join(chunks))
    return 0


if __name__ == "__main__":
    sys.exit(main())

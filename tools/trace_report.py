#!/usr/bin/env python
"""Render a phase-timing + chain-health summary from a telemetry trace.

    python tools/trace_report.py /tmp/t.jsonl            # last run in file
    python tools/trace_report.py /tmp/t.jsonl --run 1    # a specific run
    python tools/trace_report.py /tmp/t.jsonl --all      # every run
    python tools/trace_report.py /tmp/t.jsonl --json     # machine-readable

Traces are written by ``--trace PATH`` on the ``python -m stark_tpu``
subcommands, by ``bench.py`` (under the supervised workdir), or by any code
that installs a `stark_tpu.telemetry.RunTrace`.  Stdlib-only on the read
path apart from the schema helpers it shares with the writer
(`stark_tpu.telemetry`) — no jax import, so it runs anywhere the trace
file lands, including hosts with a dead accelerator tunnel.

Forward/backward compat: fields a trace predates (PR-1-era files carry no
overlap/diag accounting) render as ``n/a`` — never an error — and
``--json`` emits the raw `summarize_trace` dict, the machine contract
``tools/perf_ledger.py ingest --trace`` consumes for ledger rows.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# repo-root invocation without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from stark_tpu.telemetry import (  # noqa: E402
    PHASE_EVENTS,
    read_trace,
    summarize_trace,
)


def _fmt(v) -> str:
    # "n/a", never a crash: traces written before a field existed (e.g.
    # PR-1-era files predate the overlap/diag fields) must still render
    if v is None:
        return "n/a"
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _table(rows, header) -> str:
    """Plain aligned text table (no deps)."""
    cols = [header] + [[_fmt(c) for c in r] for r in rows]
    widths = [max(len(r[i]) for r in cols) for i in range(len(header))]
    lines = []
    for j, r in enumerate(cols):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if j == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def render_run(events, run) -> str:
    s = summarize_trace(events, run=run)
    out = []
    meta = s["meta"]
    desc = " ".join(
        f"{k}={meta[k]}"
        for k in ("entry", "model", "kernel", "chains", "num_shards",
                  "num_temps", "platform", "device_count")
        if k in meta
    )
    out.append(f"run {s['run']}: {desc or '(no run_start event)'}")
    wall = s["wall_s"] or 0.0
    phase_sum = sum(p["total_s"] for p in s["phases"].values())
    out.append(
        f"wall {wall:.2f}s, {s['events']} events, "
        f"phases cover {phase_sum:.2f}s"
        + (f" ({100.0 * phase_sum / wall:.0f}%)" if wall else "")
        + (f", {s['restarts']} restart(s)" if s["restarts"] else "")
    )
    out.append("")

    # phase table in canonical order, then any others the writer added
    order = {name: i for i, name in enumerate(PHASE_EVENTS)}
    rows = [
        (
            name,
            p["count"],
            round(p["total_s"], 3),
            f"{100.0 * p['total_s'] / wall:.1f}%" if wall else "—",
        )
        for name, p in sorted(
            s["phases"].items(), key=lambda kv: order.get(kv[0], 99)
        )
    ]
    out.append(_table(rows, ("phase", "events", "total_s", "share")))
    out.append("")

    # block-pipeline overlap accounting (runner's async sample loop):
    # host work hidden behind in-flight device blocks, and the estimated
    # device idle fraction — the number the pipeline exists to drive to 0
    ov = s.get("overlap") or {}
    if ov:
        rows = [
            ("host work hidden (s)", ov.get("t_host_hidden_s")),
            ("host wait on device (s)", ov.get("t_wait_s")),
            ("device idle (s)", ov.get("device_idle_s")),
            (
                "device idle fraction",
                f"{100.0 * ov['device_idle_frac']:.1f}%"
                if ov.get("device_idle_frac") is not None
                else None,
            ),
        ]
        out.append(_table(
            [r for r in rows if r[1] is not None], ("block overlap", "value")
        ))
        out.append("")

    # streaming-diagnostics / adaptive-scheduler accounting: what the
    # convergence gate transferred per block (constant O(chains*d*L) with
    # streaming on, growing with the history under the legacy gate), the
    # last ESS forecast, and the end-of-run overshoot estimate
    dg = s.get("diag") or {}
    if dg:
        def _bytes(v):
            return None if v is None else f"{v / 1024.0:.1f} KiB"

        rows = [
            ("streaming gate", dg.get("stream_diag")),
            ("adaptive blocks", dg.get("adaptive_blocks")),
            ("gate transfer / block (last)", _bytes(dg.get("bytes_last"))),
            ("gate transfer / block (max)", _bytes(dg.get("bytes_max"))),
            ("gate transfer total", _bytes(dg.get("bytes_total"))),
            ("ESS forecast (draws/chain)", dg.get("ess_forecast_last")),
            ("overshoot (draws/chain)", dg.get("overshoot_draws")),
        ]
        out.append(_table(
            [r for r in rows if r[1] is not None],
            ("diagnostics transfer", "value"),
        ))
        out.append("")

    # ragged-NUTS scheduling (STARK_RAGGED_NUTS): lane occupancy — the
    # useful fraction of the gradient evaluations the batched block loop
    # executed (1.0 = no lane-sync waste); present only on knob-on runs
    ns = s.get("nutssched") or {}
    if ns:
        def _pct(v):
            return None if v is None else f"{100.0 * v:.1f}%"

        rows = [
            ("step-synchronized (ragged)", ns.get("ragged")),
            ("lane occupancy (last)", _pct(ns.get("occupancy_last"))),
            ("lane occupancy (min)", _pct(ns.get("occupancy_min"))),
            ("lane occupancy (mean)", _pct(ns.get("occupancy_mean"))),
            ("scheduler iterations", ns.get("sched_iters_total")),
            ("blocks accounted", ns.get("blocks")),
        ]
        out.append(_table(
            [r for r in rows if r[1] is not None],
            ("NUTS scheduling", "value"),
        ))
        out.append("")

    # fleet-sampling accounting (stark_tpu.fleet): batch occupancy /
    # convergence rollup plus a per-problem table from the
    # problem_converged events — which posterior finished when, at what
    # gradient cost, and who straggled
    fl = s.get("fleet") or {}
    if fl:
        rows = [
            ("problems", fl.get("problems")),
            ("converged", fl.get("problems_converged")),
            ("budget exhausted", fl.get("problems_budget_exhausted")),
            # per-problem fault domains: contained lane reseeds and
            # terminal quarantines (the fleet completed DEGRADED around
            # the lost problems — per-tenant loss, not process unhealth)
            ("quarantined", fl.get("problems_quarantined")),
            ("lane reseeds", fl.get("lane_reseeds")),
            ("degraded", fl.get("degraded")),
            ("lost problems",
             ", ".join(str(p) for p in fl["lost_problems"])
             if fl.get("lost_problems") else None),
            ("fleet blocks", fl.get("blocks")),
            ("compactions", fl.get("compactions")),
            # in-place admission accounting (slot scheduler / streaming
            # feed, PR 13) — n/a on traces that predate it
            ("admissions", fl.get("admissions")),
            ("slot recycles", fl.get("slot_recycles")),
            ("queue depth (last)", fl.get("queue_depth_last")),
            ("warm-started admissions", fl.get("warmstarted")),
            ("warmup draws saved", fl.get("warmup_draws_saved")),
            ("last occupancy", fl.get("occupancy_last")),
            ("last active/batch",
             f"{fl['active_last']}/{fl['batch_last']}"
             if fl.get("active_last") is not None
             and fl.get("batch_last") is not None else None),
            ("active grad evals", fl.get("grad_evals")),
            # mesh-parallel fleet (PR 14): shard count + per-shard
            # occupancy — n/a-filtered on single-device and pre-PR-14
            # traces like every other late-addition field
            ("mesh shards", fl.get("shards")),
            ("per-shard occupancy (last)",
             ", ".join(f"{float(o):.2f}" for o in fl["shard_occupancy_last"])
             if fl.get("shard_occupancy_last") else None),
            # elastic fault domains (PR 17): shards the deadman declared
            # lost (the fleet re-packed onto the survivors) and
            # backpressure-bounced feed submissions — n/a-filtered on
            # traces that predate them
            ("lost shards",
             ", ".join(str(k) for k in fl["lost_shards"])
             if fl.get("lost_shards") else None),
            ("feed rejects", fl.get("feed_rejects")),
        ]
        out.append(_table(
            [r for r in rows if r[1] is not None], ("fleet", "value")
        ))
        out.append("")
        # admission timeline (slot scheduler / streaming feed): which
        # problem entered which slot at which block, what the queue
        # looked like, and whether warm-start transfer seeded it —
        # absent (not an error) on traces that predate the events
        admitted = [
            e for e in events
            if e.get("run") == s["run"] and e["event"] == "problem_admitted"
        ]
        if admitted:
            rows = [
                (
                    e.get("block"),
                    e.get("problem_id"),
                    e.get("slot"),
                    e.get("source"),
                    e.get("queue_depth"),
                    e.get("warmstart"),
                    e.get("warmup_draws_saved"),
                )
                for e in admitted
            ]
            out.append(_table(
                rows,
                ("block", "admitted", "slot", "source", "queue",
                 "warm-start", "warmup saved"),
            ))
            out.append("")
        done = [
            e for e in events
            if e.get("run") == s["run"]
            and e["event"] in ("problem_converged", "problem_quarantined")
        ]
        if done:
            # quarantine forensics (PR 9 fields): WHY a problem was lost
            # and where its store's forensic copy went — n/a on older
            # traces and on rows that were never quarantined
            rows = [
                (
                    e.get("problem_id"),
                    e.get("status"),
                    e.get("blocks"),
                    e.get("draws_per_chain"),
                    e.get("grad_evals"),
                    e.get("min_ess"),
                    e.get("max_rhat"),
                    e.get("reason"),
                    e.get("quarantined_store"),
                )
                for e in done
            ]
            out.append(_table(
                rows,
                ("problem", "status", "blocks", "draws/chain",
                 "grad evals", "min ESS", "max R-hat", "reason",
                 "quarantined store"),
            ))
            out.append("")

    # mesh communication observatory (parallel.primitives, PR 16):
    # accounted collective calls / predicted wire bytes / host-blocked
    # wall plus the latest straggler attribution — absent (not an
    # error) on pre-PR-16 and STARK_COMM_TELEMETRY=0 traces
    cm = s.get("comms") or {}
    if cm:
        def _bytes(v):
            return None if v is None else f"{v / 1024.0:.1f} KiB"

        rows = [
            ("accounted calls", cm.get("calls")),
            ("payload bytes", _bytes(cm.get("payload_bytes"))),
            ("wire bytes", _bytes(cm.get("wire_bytes"))),
            ("host blocked (s)", cm.get("host_blocked_s")),
            ("by primitive",
             ", ".join(
                 f"{k}x{v['calls']}"
                 for k, v in sorted(cm["by_primitive"].items())
             ) if cm.get("by_primitive") else None),
            ("shards timed", cm.get("shards")),
            ("straggler shard (last)", cm.get("straggler_shard_last")),
            ("straggler ratio (last)", cm.get("straggler_ratio_last")),
        ]
        out.append(_table(
            [r for r in rows if r[1] is not None], ("comms", "value")
        ))
        out.append("")

    # unknown event types the summarizer could not classify (newer
    # writers): counted, never dropped
    other = s.get("other") or {}
    if other:
        out.append(_table(
            sorted(other.items()), ("unrecognized event", "count")
        ))
        out.append("")

    h = s["health"]
    if h:
        keys = (
            ("mean_accept", "acceptance rate"),
            ("num_divergent", "divergences"),
            ("max_rhat", "max R-hat"),
            ("min_ess", "min ESS"),
            ("num_stuck_components", "stuck components"),
            ("step_size", "step size"),
            ("draws_per_chain", "draws/chain"),
            # statistical-health observatory (stark_tpu.health) rollup —
            # n/a-filtered on pre-PR-15 / STARK_HEALTH=0 traces; the full
            # warning + divergence-localization table is
            # tools/health_report.py
            ("warnings", "health warnings"),
        )
        rows = [(label, h[k]) for k, label in keys if k in h]
        if h.get("warning_counts"):
            rows.append((
                "warning types",
                ", ".join(
                    f"{k}x{v}" for k, v in h["warning_counts"].items()
                ),
            ))
        out.append(_table(rows, ("chain health", "value")))
    else:
        out.append("(no chain_health events)")

    # per-shard / per-replica tagged health, when the parallel paths ran
    for tag in ("shard", "replica"):
        tagged = [
            e for e in events
            if e.get("run") == s["run"] and e["event"] == "chain_health"
            and tag in e
        ]
        if not tagged:
            continue
        cols = [
            k for k in ("step_size", "traj_length", "beta",
                        "swap_accept_pair", "num_divergent")
            if any(k in e for e in tagged)
        ]
        rows = [
            tuple([e[tag]] + [e.get(k) for k in cols]) for e in tagged
        ]
        out.append("")
        out.append(_table(rows, tuple([tag] + cols)))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="JSONL trace file")
    ap.add_argument("--run", type=int, default=None,
                    help="run ordinal to report (default: last)")
    ap.add_argument("--all", action="store_true", help="report every run")
    ap.add_argument("--json", action="store_true",
                    help="print the summary dict(s) as JSON instead")
    args = ap.parse_args(argv)

    # tolerate a torn final line: the trace may still be live
    events = read_trace(args.trace, strict=False)
    if not events:
        print(f"{args.trace}: no parseable events", file=sys.stderr)
        return 1
    runs = sorted({e.get("run", 0) for e in events})
    picked = runs if args.all else [args.run if args.run is not None else runs[-1]]
    if args.json:
        out = [summarize_trace(events, run=r) for r in picked]
        print(json.dumps(out[0] if len(out) == 1 else out, indent=1))
        return 0
    chunks = [render_run(events, r) for r in picked]
    print(("\n\n" + "=" * 60 + "\n\n").join(chunks))
    return 0


if __name__ == "__main__":
    sys.exit(main())
